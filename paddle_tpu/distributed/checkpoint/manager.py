"""CheckpointManager: fault-tolerant training checkpoints.

Reference blueprint: python/paddle/distributed/checkpoint/ (sharded save +
reshard-on-load) plus the fleet elastic/recovery stack.  TVM-style
mechanism/policy separation (PAPERS.md): save_state_dict/load_state_dict in
this package are the MECHANISM (shard snapshot, reshard-on-load); this
manager is the POLICY layer — retention, atomic commits, corruption
detection, auto-resume, preemption — composed on top without growing the
primitives.

Commit protocol (docs/CHECKPOINT.md):
  1. snapshot device→host synchronously (training may mutate live state the
     moment save() returns);
  2. write shards + metadata + extras into a hidden temp directory;
  3. write MANIFEST.json (per-file sha256 + size) last, fsync it;
  4. one atomic os.rename(temp, step_XXXXXXXX).
A crash at ANY point leaves every previously committed step intact; an
uncommitted temp dir is invisible to latest_step() and swept by GC; a
committed dir damaged after the fact (bit rot, manual truncation) fails
checksum verification and is skipped by auto-resume.

Fault injection: FLAGS_checkpoint_kill_point names a protocol point
("after-shard-write" | "before-manifest" | "mid-manifest" | "after-commit")
at which the process hard-kills itself (SIGKILL) — crash consistency is
tested mechanically (tests/test_checkpoint_crash.py), not argued.

The protocol itself (temp dir -> fsynced payload -> checksummed manifest ->
one atomic rename, kill points included) is factored out as `commit_dir` so
OTHER step-directory stores ride the exact same mechanism — the serving
tier's live-engine snapshots (serving/snapshot.py, docs/CHECKPOINT.md) are
the second user: one protocol, one kill-point matrix, one sweep rule.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import re
import shutil
import signal
import threading
import time

import numpy as np

from paddle_tpu._core.flags import flag
from paddle_tpu._core.random import get_rng_state, set_rng_state
from paddle_tpu._core.tensor import Tensor

__all__ = ["CheckpointManager", "checkpoint_stats", "KILL_POINTS",
           "commit_dir", "write_payload", "sweep_stale_tmp"]

_MANIFEST = "MANIFEST.json"
_EXTRAS = "extras.pkl"
_STEP_RE = re.compile(r"^step_(\d{8})$")
_TMP_RE = re.compile(r"^_(?:tmp|old)_step_\d{8}\.(\d+)$")

KILL_POINTS = ("after-shard-write", "before-manifest", "mid-manifest", "after-commit")


# ---------------------------------------------------------------- counters
# Module-owned so profiler.checkpoint_stats() reads one schema with no
# manager handle (same contract as serving.decode_stats).
_STATS_LOCK = threading.Lock()


def _zero_stats():
    return {
        "saves": 0,
        "async_saves": 0,
        "commits": 0,
        "bytes_written": 0,
        "snapshot_seconds": 0.0,
        "write_seconds": 0.0,
        "backpressure_seconds": 0.0,
        "gc_deleted": 0,
        "restores": 0,
        "corrupt_skipped": 0,
        "errors": 0,
    }


_STATS = _zero_stats()


def _bump(**kw):
    with _STATS_LOCK:
        for k, v in kw.items():
            _STATS[k] += v


def checkpoint_stats(reset: bool = False) -> dict:
    """CheckpointManager counters: saves (async_saves of them backgrounded),
    committed step dirs, bytes/seconds split into snapshot (synchronous
    device→host) vs write (disk), backpressure_seconds save() spent blocked
    on an in-flight write, GC deletions, restores, and checkpoints skipped
    as corrupt/torn during auto-resume."""
    with _STATS_LOCK:
        out = dict(_STATS)
        if reset:
            _STATS.update(_zero_stats())
    return out


# ----------------------------------------------------------- fault injection
def _maybe_kill(point: str):
    """Dev-mode crash injection: if FLAGS_checkpoint_kill_point names this
    protocol point, hard-kill the process (SIGKILL — no atexit, no flushes,
    exactly what preemption looks like)."""
    if flag("FLAGS_checkpoint_kill_point") == point:
        os.kill(os.getpid(), signal.SIGKILL)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _split_tensors(tree):
    """Split a nested state dict into (tensor_tree, extra_tree): Tensor
    leaves go through the sharded reshard-on-load store, everything else
    (scheduler scalars, step counts, LBFGS history arrays) rides the pickled
    extras file."""
    tensors, extras = {}, {}
    for k, v in tree.items():
        if isinstance(v, dict):
            t, e = _split_tensors(v)
            if t:
                tensors[k] = t
            if e:
                extras[k] = e
        elif isinstance(v, Tensor):
            tensors[k] = v
        else:
            extras[k] = v
    return tensors, extras


def commit_dir(base_dir, final_name, writer, manifest_extra=None):
    """The shared atomic commit protocol (docs/CHECKPOINT.md):

      1. create a hidden ``_tmp_{final_name}.{pid}`` directory;
      2. ``writer(tmp)`` writes + fsyncs the payload files, returning the
         bytes it wrote (it injects its own "after-shard-write" /
         "before-manifest" kill points via `_maybe_kill`);
      3. MANIFEST.json (per-file sha256 + size) written LAST and fsynced,
         with the "mid-manifest" kill point inside;
      4. an existing ``final_name`` is renamed aside (re-save of the same
         step: new data is fully on disk before the old dir moves);
      5. ONE atomic ``os.rename(tmp, final)`` — THE commit point — then the
         parent directory is fsynced, the displaced dir deleted, and the
         "after-commit" kill point fires.

    Returns ``(final_path, total_bytes_written)``.  Both CheckpointManager
    and the serving tier's EngineSnapshot commit through this one function,
    so the SIGKILL matrix proves them together."""
    if not _STEP_RE.match(final_name):
        # the crash-abandoned temp/displaced dirs are swept by pattern
        # (_TMP_RE); an unmatchable final_name would leak them forever
        raise ValueError(
            f"commit_dir final_name must be step-tagged (step_XXXXXXXX, "
            f"sweepable after a crash): got {final_name!r}")
    tmp = os.path.join(base_dir, f"_tmp_{final_name}.{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    written = writer(tmp)

    manifest = {
        "format": 1,
        "files": {
            name: {
                "sha256": _sha256_file(os.path.join(tmp, name)),
                "size": os.path.getsize(os.path.join(tmp, name)),
            }
            for name in sorted(os.listdir(tmp))
        },
    }
    if manifest_extra:
        manifest.update(manifest_extra)
    data = json.dumps(manifest, indent=1, sort_keys=True)
    mpath = os.path.join(tmp, _MANIFEST)
    with open(mpath, "w") as f:
        if flag("FLAGS_checkpoint_kill_point") == "mid-manifest":
            f.write(data[: len(data) // 2])
            f.flush()
            os.fsync(f.fileno())
            _maybe_kill("mid-manifest")
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    written += os.path.getsize(mpath)

    final = os.path.join(base_dir, final_name)
    displaced = None
    if os.path.exists(final):  # re-save of the same step
        displaced = os.path.join(base_dir, f"_old_{final_name}.{os.getpid()}")
        shutil.rmtree(displaced, ignore_errors=True)
        os.rename(final, displaced)
    os.rename(tmp, final)  # THE commit point: atomic within one fs
    _fsync_dir(base_dir)
    if displaced is not None:
        shutil.rmtree(displaced, ignore_errors=True)
    _maybe_kill("after-commit")
    return final, written


def write_payload(tmp, arrays, fname, metadata_json, extras_blob):
    """The shared `commit_dir` payload writer: npz shards (fsynced, then
    the "after-shard-write" kill point), metadata.json + extras.pkl
    (fsynced, then "before-manifest").  Returns bytes written.  ONE body
    for CheckpointManager._commit and EngineSnapshot.save — a new kill
    point or fsync fix lands in both tiers at once."""
    written = 0
    shard_path = os.path.join(tmp, fname)
    with open(shard_path, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    written += os.path.getsize(shard_path)
    _maybe_kill("after-shard-write")

    from . import _META_FILE

    meta_path = os.path.join(tmp, _META_FILE)
    with open(meta_path, "w") as f:
        f.write(metadata_json)
        f.flush()
        os.fsync(f.fileno())
    extras_path = os.path.join(tmp, _EXTRAS)
    with open(extras_path, "wb") as f:
        f.write(extras_blob)
        f.flush()
        os.fsync(f.fileno())
    written += os.path.getsize(meta_path) + os.path.getsize(extras_path)
    _maybe_kill("before-manifest")
    return written


def sweep_stale_tmp(base_dir):
    """Delete ``_tmp_*``/``_old_*`` working directories whose owning pid is
    dead (a hard-killed process abandons at most its in-flight temp dir —
    committed steps are untouchable by design).  Returns the sweep count."""
    swept = 0
    for name in os.listdir(base_dir):
        m = _TMP_RE.match(name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid == os.getpid():
            continue  # possibly our own in-flight write
        try:
            os.kill(pid, 0)
            continue  # owner still alive
        except ProcessLookupError:
            pass  # dead: safe to sweep
        except OSError:
            continue  # e.g. EPERM — owner alive under another uid
        shutil.rmtree(os.path.join(base_dir, name), ignore_errors=True)
        swept += 1
    return swept


class _CommitJob:
    __slots__ = ("step", "arrays", "metadata", "fname", "extras_blob")

    def __init__(self, step, arrays, metadata, fname, extras_blob):
        self.step = step
        self.arrays = arrays
        self.metadata = metadata
        self.fname = fname
        self.extras_blob = extras_blob


class CheckpointManager:
    """Owns step-tagged checkpoint directories under `dir` and the full
    save/restore lifecycle of a training job.

        mgr = CheckpointManager("ckpts", save_interval_steps=100,
                                max_to_keep=3, async_save=True)
        start = mgr.restore(model=m, optimizer=opt, dataloader=dl) or 0
        for step in range(start + 1, total + 1):
            ...train...
            mgr.maybe_save(step, model=m, optimizer=opt, dataloader=dl)
        mgr.wait()

    Restores route tensor state through load_state_dict's reshard-on-load,
    so resuming under a DIFFERENT parallel topology works through this same
    API.  Restored state covers model params, optimizer accumulators +
    LR scheduler + step count, the global RNG (seed, counter), and the
    DataLoader/sampler position — a killed-and-resumed run reproduces the
    uninterrupted run's per-step losses bit-for-bit.
    """

    def __init__(self, dir, save_interval_steps=1000, max_to_keep=5,
                 async_save=True, max_pending=1):
        if max_to_keep is not None and max_to_keep < 1:
            raise ValueError("max_to_keep must be >= 1 (or None for unlimited)")
        self.dir = str(dir)
        self.save_interval_steps = int(save_interval_steps)
        self.max_to_keep = max_to_keep
        self.async_save = bool(async_save)
        os.makedirs(self.dir, exist_ok=True)

        self._queue: queue.Queue = queue.Queue(maxsize=max(1, int(max_pending)))
        self._worker = None
        self._worker_lock = threading.Lock()
        self._error = None  # first background failure, re-raised on next call
        self._valid_cache: dict = {}  # step dir -> (manifest mtime, bool)
        self._skip_counted: set = set()  # torn dirs already counted in stats

        self._preempt_requested = False
        self._preempt_saved = False
        self._prev_handlers: dict = {}

        self.restored_extra_state = None

    # ------------------------------------------------------------- layout
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{int(step):08d}")

    def all_steps(self) -> list:
        """Committed step numbers, ascending (validity not checked)."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.dir, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        """Newest step whose checkpoint passes checksum verification, or
        None.  Torn/corrupt directories are skipped (and counted in
        checkpoint_stats()['corrupt_skipped']), so auto-resume always lands
        on the newest LOADABLE state."""
        self._raise_pending()
        for step in reversed(self.all_steps()):
            if self._verify_dir(self._step_dir(step)):
                return step
            path = self._step_dir(step)
            if path not in self._skip_counted:  # count each torn dir once
                self._skip_counted.add(path)
                _bump(corrupt_skipped=1)
        return None

    # ------------------------------------------------------------- verify
    def _verify_dir(self, path: str) -> bool:
        mpath = os.path.join(path, _MANIFEST)
        try:
            mtime = os.stat(mpath).st_mtime_ns
        except OSError:
            return False
        cached = self._valid_cache.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
        ok = self._verify_manifest(path, mpath)
        self._valid_cache[path] = (mtime, ok)
        return ok

    @staticmethod
    def _verify_manifest(path: str, mpath: str) -> bool:
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            files = manifest["files"]
        except (OSError, ValueError, KeyError):
            return False  # torn or unparsable manifest
        for name, rec in files.items():
            fpath = os.path.join(path, name)
            try:
                if os.path.getsize(fpath) != rec["size"]:
                    return False
                if _sha256_file(fpath) != rec["sha256"]:
                    return False
            except (OSError, KeyError):
                return False
        return True

    # --------------------------------------------------------------- save
    def save(self, step, model=None, optimizer=None, lr_scheduler=None,
             dataloader=None, extra_state=None):
        """Checkpoint `step` unconditionally.  Snapshots device→host NOW
        (synchronously); with async_save the disk write + atomic commit run
        on the supervised background thread — save() blocks only when a
        previous write is still in flight (backpressure), and any background
        failure re-raises on the next manager call."""
        self._raise_pending()
        step = int(step)
        t0 = time.perf_counter()

        tensors = {}
        extras = {"step": step, "rng": list(get_rng_state())}
        if model is not None:
            sd = model.state_dict() if hasattr(model, "state_dict") else dict(model)
            t, e = _split_tensors(sd)
            tensors["model"] = t
            if e:
                extras["model"] = e
        if optimizer is not None:
            t, e = _split_tensors(optimizer.state_dict())
            if t:
                tensors["optimizer"] = t
            if e:
                extras["optimizer"] = e
        if lr_scheduler is not None:
            extras["lr_scheduler"] = lr_scheduler.state_dict()
        if dataloader is not None:
            extras["dataloader"] = dataloader.state_dict()
        if extra_state is not None:
            extras["extra_state"] = extra_state

        from . import build_shard_snapshot

        arrays, md, fname = build_shard_snapshot(tensors)
        extras_blob = pickle.dumps(extras, protocol=4)
        _bump(saves=1, snapshot_seconds=time.perf_counter() - t0)

        job = _CommitJob(step, arrays, md, fname, extras_blob)
        if not self.async_save:
            self._commit(job)
            self._raise_pending()
            return

        self._ensure_worker()
        tq = time.perf_counter()
        self._queue.put(job)  # blocks when a write is in flight: backpressure
        _bump(async_saves=1, backpressure_seconds=time.perf_counter() - tq)

    def maybe_save(self, step, **components) -> bool:
        """Save when `step` hits the save interval or a preemption signal
        arrived (install_preemption_handler) — the step-boundary final
        checkpoint.  Returns True when a save was issued."""
        step = int(step)
        due = self._preempt_requested or (
            self.save_interval_steps > 0 and step % self.save_interval_steps == 0
        )
        if not due:
            return False
        self.save(step, **components)
        if self._preempt_requested:
            self._preempt_saved = True
        return True

    # ------------------------------------------------------ background IO
    def _ensure_worker(self):
        with self._worker_lock:
            if self._worker is not None and self._worker.is_alive():
                return
            # Daemon + atexit drain: a normal exit flushes pending writes
            # (wait() re-raises failures); a hard kill abandons at most the
            # in-flight TEMP dir — committed steps are untouchable by design.
            self._worker = threading.Thread(
                target=self._worker_loop, name="CheckpointManager", daemon=True
            )
            self._worker.start()
            import atexit

            atexit.register(self.wait)

    def _worker_loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            try:
                self._commit(job)
            except BaseException as e:
                if self._error is None:
                    self._error = e
                _bump(errors=1)
            finally:
                self._queue.task_done()

    def wait(self):
        """Join all outstanding async writes; re-raise the first background
        failure.  Safe to call any time (idle manager: no-op)."""
        if self._worker is not None:
            self._queue.join()
        self._raise_pending()

    def _raise_pending(self):
        err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                f"checkpoint background write failed in {self.dir!r}"
            ) from err

    # --------------------------------------------------------- commit core
    def _commit(self, job: _CommitJob):
        t0 = time.perf_counter()

        def writer(tmp):
            return write_payload(tmp, job.arrays, job.fname,
                                 job.metadata.to_json(), job.extras_blob)

        self._valid_cache.pop(self._step_dir(job.step), None)
        final, written = commit_dir(self.dir, f"step_{job.step:08d}", writer,
                                    manifest_extra={"step": job.step})
        _bump(commits=1, bytes_written=written,
              write_seconds=time.perf_counter() - t0)

        if flag("FLAGS_checkpoint_verify_on_save"):
            if not self._verify_dir(final):
                raise RuntimeError(f"post-commit verification failed for {final}")
        else:
            # every byte was hashed moments ago while writing the manifest —
            # seed the verify cache so _gc/latest_step don't read it all back
            mpath = os.path.join(final, _MANIFEST)
            self._valid_cache[final] = (os.stat(mpath).st_mtime_ns, True)
        self._gc()

    # ----------------------------------------------------------------- gc
    def _gc(self):
        """Retention: keep the newest `max_to_keep` VALID steps.  Invalid
        (torn/corrupt) committed dirs are deleted only when a newer valid
        checkpoint exists, and the last valid checkpoint is never deleted.
        Stale temp dirs from dead processes are swept too."""
        steps = self.all_steps()
        valid = [s for s in steps if self._verify_dir(self._step_dir(s))]
        keep = set(valid if self.max_to_keep is None else valid[-self.max_to_keep:])
        newest_valid = valid[-1] if valid else None
        for s in steps:
            if s in keep:
                continue
            if s not in valid and (newest_valid is None or s > newest_valid):
                # torn dir newer than every valid checkpoint: keep for
                # post-mortem (it is skipped by latest_step anyway)
                continue
            path = self._step_dir(s)
            shutil.rmtree(path, ignore_errors=True)
            self._valid_cache.pop(path, None)
            _bump(gc_deleted=1)

        swept = sweep_stale_tmp(self.dir)
        if swept:
            _bump(gc_deleted=swept)

    # -------------------------------------------------------------- restore
    def restore(self, model=None, optimizer=None, lr_scheduler=None,
                dataloader=None, step=None):
        """Restore training state from `step` (default: latest valid).
        Returns the restored step number, or None when no valid checkpoint
        exists (fresh start).  Tensor state loads through load_state_dict's
        reshard-on-load, so the CURRENT sharding of every tensor — possibly
        a different mesh/topology than at save time — is honored."""
        self.wait()
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        path = self._step_dir(step)
        if not self._verify_dir(path):
            raise RuntimeError(f"checkpoint {path} is missing or corrupt")

        with open(os.path.join(path, _EXTRAS), "rb") as f:
            extras = pickle.load(f)

        request = {}
        if model is not None:
            sd = model.state_dict() if hasattr(model, "state_dict") else dict(model)
            t, _ = _split_tensors(sd)
            request["model"] = t
        if optimizer is not None:
            self._materialize_accumulators(optimizer)
            t, _ = _split_tensors(optimizer.state_dict())
            if t:
                request["optimizer"] = t
        if request:
            from . import _META_FILE, load_state_dict
            from .metadata import Metadata

            with open(os.path.join(path, _META_FILE)) as f:
                saved = set(Metadata.from_json(f.read()).tensors)
            request = _prune_to_saved(request, saved)
            load_state_dict(request, path)

        if "rng" in extras:
            set_rng_state(tuple(extras["rng"]))
        if optimizer is not None and "optimizer" in extras:
            optimizer.set_state_dict(extras["optimizer"])
        if lr_scheduler is not None and "lr_scheduler" in extras:
            lr_scheduler.set_state_dict(extras["lr_scheduler"])
        if dataloader is not None and "dataloader" in extras:
            dataloader.set_state_dict(extras["dataloader"])
        self.restored_extra_state = extras.get("extra_state")
        _bump(restores=1)
        return step

    @staticmethod
    def _materialize_accumulators(optimizer):
        """A fresh optimizer creates its accumulators lazily on the first
        step(); restore needs them to exist NOW so the sharded loader can
        fill them in place.  The rolled-back dry step the static path uses
        for accumulator discovery does exactly this (no-op for LBFGS, whose
        step needs a closure and whose history rides the extras file)."""
        if optimizer._accumulators:
            return
        params = [p for p in optimizer._parameter_list if not p.stop_gradient]
        if not params:
            return
        try:
            optimizer._journaled_step(params)
        except TypeError:
            pass  # closure-based step (LBFGS): no per-param accumulators

    # ----------------------------------------------------------- preemption
    def install_preemption_handler(self, signals=(signal.SIGTERM,)):
        """SIGTERM-style preemption: the handler only flips a flag; the next
        maybe_save() at a step boundary writes the final checkpoint (async
        signal context is no place for disk IO).  Check `preemption_saved`
        in the training loop to exit cleanly."""

        def _handler(signum, frame):
            self._preempt_requested = True

        for s in signals:
            self._prev_handlers[s] = signal.signal(s, _handler)

    def uninstall_preemption_handler(self):
        for s, prev in self._prev_handlers.items():
            signal.signal(s, prev)
        self._prev_handlers.clear()

    @property
    def preemption_requested(self) -> bool:
        return self._preempt_requested

    @property
    def preemption_saved(self) -> bool:
        """True once a preemption-triggered checkpoint has been issued."""
        return self._preempt_saved

    # -------------------------------------------------------------- cleanup
    def close(self):
        """Drain pending writes and stop the background worker."""
        if self._worker is not None and self._worker.is_alive():
            self._queue.join()
            self._queue.put(None)
            self._worker.join(timeout=60)
        self.uninstall_preemption_handler()
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _prune_to_saved(request, saved_names, prefix=""):
    """Drop requested tensors the checkpoint does not contain (e.g. restoring
    an optimizer into a run saved without one) instead of KeyError-ing the
    whole restore; warn so silent drift is visible."""
    import warnings

    out = {}
    for k, v in request.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            sub = _prune_to_saved(v, saved_names, name + ".")
            if sub:
                out[k] = sub
        elif name in saved_names:
            out[k] = v
        else:
            warnings.warn(
                f"checkpoint has no tensor {name!r}; leaving current value",
                stacklevel=3,
            )
    return out
