"""Checkpoint metadata (reference:
python/paddle/distributed/checkpoint/metadata.py — LocalTensorMetadata/
LocalTensorIndex/Metadata keyed by (tensor_name, global_offset)).

The global metadata maps every saved shard of every tensor to
(file, key, global_offset, local_shape) so a loader under ANY topology can
assemble exactly the regions it needs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict


@dataclass
class ShardRecord:
    file: str  # npz file (relative to checkpoint dir)
    key: str  # array key inside the npz
    global_offset: list  # start index per dim
    local_shape: list  # shard shape


@dataclass
class TensorMetadata:
    name: str
    global_shape: list
    dtype: str
    shards: list = field(default_factory=list)  # list[ShardRecord]


@dataclass
class Metadata:
    tensors: dict = field(default_factory=dict)  # name -> TensorMetadata
    flat_mapping: dict = field(default_factory=dict)  # state_dict key path info

    def to_json(self) -> str:
        return json.dumps(
            {
                "tensors": {k: asdict(v) for k, v in self.tensors.items()},
                "flat_mapping": self.flat_mapping,
            },
            indent=1,
        )

    @classmethod
    def from_json(cls, text: str) -> "Metadata":
        raw = json.loads(text)
        md = cls()
        md.flat_mapping = raw.get("flat_mapping", {})
        for k, tv in raw["tensors"].items():
            tm = TensorMetadata(tv["name"], tv["global_shape"], tv["dtype"])
            tm.shards = [ShardRecord(**s) for s in tv["shards"]]
            md.tensors[k] = tm
        return md
