"""ProcessGroup: eager cross-process collectives with the async-Task API.

Reference: paddle/fluid/distributed/collective/process_group.h:47 (async
ops returning event-backed Tasks), process_group_nccl.h:37 (per-device comm
streams, ring ids), nccl_comm_context.h.

TPU-native redesign (SURVEY.md §7 "ProcessGroup-on-XLA"): there are no
comm streams to manage — an eager collective outside any compiled program
is itself a tiny COMPILED COLLECTIVE EXECUTABLE.  For a group spanning the
multi-controller world (jax.distributed initialized, one process per host):

  local value --make_array(global mesh over the ring)--> global jax.Array
  --cached jitted psum/all_gather/...--> async result --Task

The executable is cached per (op, shape, dtype, ring) — the KernelKey-style
dispatch cache the survey calls for — so repeated small collectives (global
norm terms, scalar broadcasts) pay dispatch, not compilation.  XLA runs the
collective asynchronously; Task.wait blocks on the result buffer (watchdog-
guarded), Task.is_completed polls it — the event-backed Task contract.

Single-process groups short-circuit (the reference's nranks==1 fast path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ProcessGroup", "P2POp", "batch_isend_irecv", "UnmatchedP2PError"]


class Task:
    """Async collective handle (reference process_group.h Task)."""

    def __init__(self, result=None, group=None, name="collective"):
        self._result = result
        self._group = group
        self._name = name

    def wait(self, timeout=None):
        if self._result is not None and hasattr(self._result, "block_until_ready"):
            from paddle_tpu.distributed.communication.watchdog import comm_watch

            with comm_watch(self._name, group=self._group, timeout=timeout):
                self._result.block_until_ready()
        return True

    def is_completed(self):
        r = self._result
        if r is None or not hasattr(r, "is_ready"):
            return True
        return bool(r.is_ready())

    def result(self):
        return self._result


class ProcessGroup:
    """A ring of PROCESSES (multi-controller) issuing compiled collectives."""

    def __init__(self, ranks=None, ring_id=0, name=None):
        self.ranks = list(ranks) if ranks is not None else list(range(jax.process_count()))
        self.ring_id = ring_id
        self._name = name or f"pg_{ring_id}"
        self._cache: dict = {}  # (op, shape, dtype) -> compiled fn
        self._mesh = None

    @property
    def nranks(self):
        return len(self.ranks)

    size = nranks

    def rank(self):
        return self.ranks.index(jax.process_index()) if jax.process_index() in self.ranks else -1

    # ------------------------------------------------------------- plumbing
    def _ring_mesh(self):
        """One mesh axis over the ring's processes (one device per process:
        the process-leader device, matching one-NCCL-rank-per-proc)."""
        if self._mesh is None:
            devs = []
            for r in self.ranks:
                cands = [d for d in jax.devices() if d.process_index == r]
                if not cands:
                    raise RuntimeError(f"process {r} has no devices visible")
                devs.append(cands[0])
            self._mesh = jax.sharding.Mesh(np.asarray(devs), ("ring",))
        return self._mesh

    def _global(self, value):
        """Lift the local value to a ring-global array [nranks, ...]."""
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self._ring_mesh()
        sharding = NamedSharding(mesh, PartitionSpec("ring"))
        local = jnp.asarray(value)[None]
        return jax.make_array_from_single_device_arrays(
            (self.nranks,) + tuple(local.shape[1:]), sharding, [local]
        )

    def _compiled(self, op_name, builder, value):
        key = (op_name, tuple(value.shape), str(value.dtype), tuple(self.ranks))
        fn = self._cache.get(key)
        if fn is None:
            fn = builder()
            self._cache[key] = fn
        return fn

    def cache_size(self):
        return len(self._cache)

    def _run(self, op_name, value, body, out_spec):
        """Compile-and-cache a shard_map collective over the ring."""
        from jax.sharding import NamedSharding, PartitionSpec

        if self.nranks == 1:
            return value, None
        mesh = self._ring_mesh()

        def builder():
            from paddle_tpu.distributed.shard_map_compat import shard_map

            f = shard_map(
                body, mesh=mesh, in_specs=PartitionSpec("ring"),
                out_specs=out_spec, axis_names={"ring"},
            )
            return jax.jit(f)

        fn = self._compiled(op_name, builder, value)
        from paddle_tpu._core import flags as _flags

        if _flags.flag("FLAGS_verify_sharding"):
            # mesh lint the collective executable ABSTRACTLY before its
            # first execution on this ring (per compiled signature): a bad
            # pair permutation or mis-axised body is a named error here,
            # not a rendezvous that strands the peer processes
            key = ("linted", op_name, tuple(jnp.shape(value)),
                   str(jnp.result_type(value)), tuple(self.ranks))
            if key not in self._cache:
                from paddle_tpu.static.mesh_lint import MeshLinter, _finish

                aval = jax.ShapeDtypeStruct(
                    (self.nranks,) + tuple(jnp.shape(value)),
                    jnp.result_type(value))
                linter = MeshLinter(mesh={"ring": self.nranks})
                _finish(linter.lint_callable(
                            fn, aval, site=f"ProcessGroup.{op_name}"),
                        f"Mesh lint failed (ProcessGroup.{op_name})",
                        raise_on_error=True)
                self._cache[key] = True
        garr = self._global(value)
        # the execute blocks on peers joining: watchdog-guard it so a dead
        # rank produces a loud timeout (+ creation stack) instead of a
        # silent hang (reference CommTask / comm_task_manager.h:37)
        from paddle_tpu.distributed.communication.watchdog import comm_watch

        with comm_watch(op_name, group=self):
            out = fn(garr)
            jax.block_until_ready(out)
        return out, out

    # ----------------------------------------------------------- collectives
    def allreduce(self, tensor, op="sum"):
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "allreduce")
        red = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin, "avg": lax.pmean}[op]

        def body(x):  # x: [1, ...] local slice
            return red(x, "ring")

        out, _ = self._run(f"allreduce_{op}", v, body, PartitionSpec("ring"))
        # every slice holds the reduction; read the local one
        local = out.addressable_shards[0].data[0]
        if isinstance(tensor, Tensor):
            tensor._bind(local)
        return Task(local, self, "allreduce")

    def allgather(self, tensor):
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v[None], self, "allgather")

        def body(x):
            return lax.all_gather(x[0], "ring")

        out, _ = self._run("allgather", v, body, PartitionSpec("ring"))
        return Task(out.addressable_shards[0].data, self, "allgather")

    def broadcast(self, tensor, src=0):
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "broadcast")
        src_idx = self.ranks.index(src)

        def body(x):
            return lax.all_gather(x[0], "ring")[src_idx][None]

        out, _ = self._run(f"broadcast_{src_idx}", v, body, PartitionSpec("ring"))
        local = out.addressable_shards[0].data[0]
        if isinstance(tensor, Tensor):
            tensor._bind(local)
        return Task(local, self, "broadcast")

    def reduce_scatter(self, tensor, op="sum"):
        """Input [nranks*chunk, ...] per rank; each keeps its reduced chunk."""
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "reduce_scatter")

        def body(x):
            return lax.psum_scatter(x[0], "ring", scatter_dimension=0, tiled=True)[None]

        out, _ = self._run("reduce_scatter", v, body, PartitionSpec("ring"))
        return Task(out.addressable_shards[0].data[0], self, "reduce_scatter")

    def barrier(self):
        t = self.allreduce(jnp.zeros((), jnp.int32))
        t.wait()
        return t

    # ------------------------------------------------------------------ p2p
    def _pair_group(self, a, b):
        """2-endpoint subgroup for pairwise transfers: ONLY the two endpoint
        processes execute the pair's executable, so p2p in a world > 2 does
        not require bystander ranks to join a whole-ring collective (which
        would deadlock them)."""
        if self.nranks == 2:
            return self
        key = tuple(sorted((a, b)))
        cache = getattr(self, "_pair_groups", None)
        if cache is None:
            cache = self._pair_groups = {}
        pg = cache.get(key)
        if pg is None:
            pg = ProcessGroup(ranks=list(key), ring_id=self.ring_id,
                              name=f"{self._name}_pair_{key[0]}_{key[1]}")
            cache[key] = pg
        return pg

    def _p2p(self, value, src, dst):
        """One ppermute hop src->dst over the {src, dst} pair subgroup (the
        NCCL send/recv pair of p2p_communication.py, compiled once per
        (shape, dtype, src, dst))."""
        from jax import lax
        from jax.sharding import PartitionSpec

        pg = self._pair_group(src, dst)
        si, di = pg.ranks.index(src), pg.ranks.index(dst)

        def body(x):
            return lax.ppermute(x, "ring", [(si, di)])

        out, _ = pg._run(f"p2p_{si}_{di}", value, body, PartitionSpec("ring"))
        return out.addressable_shards[0].data[0]

    def send(self, tensor, dst):
        from paddle_tpu._core.tensor import Tensor

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "send")
        me = self.ranks[self.rank()]
        self._p2p(v, src=me, dst=dst)
        return Task(v, self, "send")

    def recv(self, tensor, src):
        """tensor supplies the receive buffer's shape/dtype; the received
        payload is bound back into it (reference recv semantics)."""
        from paddle_tpu._core.tensor import Tensor

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "recv")
        me = self.ranks[self.rank()]
        got = self._p2p(v, src=src, dst=me)
        if isinstance(tensor, Tensor):
            tensor._bind(got)
        return Task(got, self, "recv")

    # --------------------------------------------------- scatter / alltoall
    def scatter(self, tensor, src=0):
        """Input on every rank: [nranks*chunk, ...]; each rank keeps src's
        chunk for its own index."""
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "scatter")
        n = self.nranks
        if v.shape[0] % n:
            raise ValueError(
                f"scatter: leading dim {v.shape[0]} not divisible by "
                f"nranks {n}"
            )
        chunk = v.shape[0] // n
        src_idx = self.ranks.index(src)

        def body(x):
            g = lax.all_gather(x[0], "ring")[src_idx]
            me = lax.axis_index("ring")
            return lax.dynamic_slice_in_dim(g, me * chunk, chunk, 0)[None]

        out, _ = self._run(f"scatter_{src_idx}", v, body, PartitionSpec("ring"))
        return Task(out.addressable_shards[0].data[0], self, "scatter")

    def alltoall(self, tensor):
        """[nranks*chunk, ...] per rank; chunk i goes to rank i."""
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "alltoall")
        if v.shape[0] % self.nranks:
            raise ValueError(
                f"alltoall: leading dim {v.shape[0]} not divisible by "
                f"nranks {self.nranks}"
            )

        def body(x):
            return lax.all_to_all(x, "ring", split_axis=1, concat_axis=1, tiled=True)

        out, _ = self._run("alltoall", v, body, PartitionSpec("ring"))
        return Task(out.addressable_shards[0].data[0], self, "alltoall")

    def reduce(self, tensor, dst=0, op="sum"):
        """Reference reduce: result is only meaningful on dst (here every
        rank computes it — XLA collectives are rank-symmetric)."""
        return self.allreduce(tensor, op=op)


class P2POp:
    """Batched p2p descriptor (reference batch_isend_irecv)."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op  # "isend" | "irecv"
        self.tensor = tensor
        self.peer = peer
        self.group = group


class UnmatchedP2PError(RuntimeError):
    """A posted send/recv found no counterpart within the timeout — the
    loud version of the hang the reference's NCCL group launch produces."""


# per-process FIFO tag counters per (group, DIRECTED rank pair): the k-th
# send src->dst matches the k-th recv src->dst posted anywhere on the
# receiver within the same group (NCCL's implicit FIFO channel ordering)
_p2p_dir_tags: dict = {}
# per (group, unordered pair): how many slot-ordered transfers this process
# has executed — both endpoints execute a pair's transfers in SLOT order
_p2p_pair_done: dict = {}


def _is_send(op):
    # accept the reference's callable form (P2POp(dist.isend, ...)) and
    # the string form
    name = op if isinstance(op, str) else getattr(op, "__name__", "")
    if name not in ("isend", "irecv", "send", "recv"):
        raise ValueError(f"P2POp.op must be isend/irecv, got {op!r}")
    return name in ("isend", "send")


def _p2p_group_key(p):
    """Identical on both endpoints; namespaces tags/slots so groups with
    the same rank pair cannot cross-match."""
    if p.group is None:
        return "world"
    return f"g{p.group.ring_id}." + ".".join(str(r) for r in p.group.ranks)


def _coordinated_batch(p2p_op_list, store, me, timeout_ms=60_000):
    """Store-coordinated pattern resolution (VERDICT r3 #9; reference
    four_directions_p2p_communication.py capability).

    Protocol (race-free by construction):
    1. every rank publishes a DESCRIPTOR per op (shape/dtype) keyed by
       (group, direction, FIFO tag);
    2. the SENDER of a transfer — and only the sender — proposes it into
       the next per-pair SLOT (store.add is atomic) once the receiver's
       descriptor is visible and the sender's lower tags of that direction
       are already proposed;
    3. both endpoints execute their pair's transfers strictly in slot
       order, so they can never disagree on ordering no matter how the
       store sweeps interleave;
    4. anything still unexecuted at the deadline raises UnmatchedP2PError
       naming the ops — never a silent hang — and FIFO tags roll back so a
       failed probe does not desync later matched transfers (ghost slots
       and descriptors are re-matched when the op is legitimately
       re-posted at the same tag).
    """
    import json as _json
    import time as _time

    ops = []
    for p in p2p_op_list:
        is_send = _is_send(p.op)
        gk = _p2p_group_key(p)
        src, dst = (me, p.peer) if is_send else (p.peer, me)
        tag = _p2p_dir_tags.get((gk, src, dst), 0)
        _p2p_dir_tags[(gk, src, dst)] = tag + 1
        t = p.tensor._value if hasattr(p.tensor, "_value") else p.tensor
        desc = {"shape": list(t.shape), "dtype": str(t.dtype)}
        ops.append({"gk": gk, "src": src, "dst": dst, "tag": tag,
                    "is_send": is_send, "p": p, "desc": desc})

    # 1. publish all descriptors first (set() also overwrites any ghost
    # descriptor left by a previously failed probe at the same tag)
    for o in ops:
        role = "s" if o["is_send"] else "r"
        store.set(
            f"p2p/{o['gk']}/{o['src']}-{o['dst']}/{o['tag']}/{role}",
            _json.dumps(o["desc"]).encode())

    def _peek(key):
        try:
            return store.get(key, timeout_ms=1)
        except Exception:
            return None

    def _pair_key(o):
        a, b = sorted((o["src"], o["dst"]))
        return f"{o['gk']}/{a}-{b}"

    def _pg_for(p):
        if p.group is not None:
            return p.group
        from paddle_tpu.distributed.communication.ops import _process_group_for

        return _process_group_for(None)

    tasks: list = [None] * len(ops)
    remaining = dict(enumerate(ops))
    proposed: set = set()
    deadline = _time.monotonic() + timeout_ms / 1e3
    try:
        while remaining:
            progress = False

            # 2. sender proposals
            for i, o in sorted(remaining.items()):
                if not o["is_send"] or i in proposed:
                    continue
                # direction FIFO: propose tags in order within this batch
                if any(o2["is_send"] and i2 not in proposed
                       and (o2["gk"], o2["src"], o2["dst"]) == (o["gk"], o["src"], o["dst"])
                       and o2["tag"] < o["tag"]
                       for i2, o2 in remaining.items()):
                    continue
                raw = _peek(f"p2p/{o['gk']}/{o['src']}-{o['dst']}/{o['tag']}/r")
                if raw is None:
                    continue
                peer_desc = _json.loads(raw if isinstance(raw, str) else raw.decode())
                if peer_desc != o["desc"]:
                    raise ValueError(
                        f"rank {me}: send {o['src']}->{o['dst']} tag "
                        f"{o['tag']} descriptor mismatch: local {o['desc']} "
                        f"vs peer {peer_desc}")
                pk = _pair_key(o)
                slot = store.add(f"p2pslot/{pk}/next", 1) - 1
                store.set(f"p2pslot/{pk}/{slot}",
                          _json.dumps([o["src"], o["dst"], o["tag"]]).encode())
                proposed.add(i)
                progress = True

            # 3. slot-ordered execution per pair
            for pk in sorted({_pair_key(o) for o in remaining.values()}):
                k = _p2p_pair_done.get(pk, 0)
                raw = _peek(f"p2pslot/{pk}/{k}")
                if raw is None:
                    continue
                ident = tuple(_json.loads(raw if isinstance(raw, str) else raw.decode()))
                mine = next(
                    (i for i, o in remaining.items()
                     if (o["src"], o["dst"], o["tag"]) == ident and _pair_key(o) == pk),
                    None)
                if mine is None:
                    # the slot's transfer is not in this batch (a ghost from
                    # a failed probe, or one of our future calls): the pair
                    # stalls here — slot order is never violated
                    continue
                o = remaining[mine]
                pg = _pg_for(o["p"])
                tasks[mine] = (pg.send(o["p"].tensor, o["dst"]) if o["is_send"]
                               else pg.recv(o["p"].tensor, o["src"]))
                _p2p_pair_done[pk] = k + 1
                del remaining[mine]
                proposed.discard(mine)
                progress = True

            if remaining:
                if progress:
                    deadline = _time.monotonic() + timeout_ms / 1e3
                elif _time.monotonic() > deadline:
                    missing = [
                        f"{'send' if o['is_send'] else 'recv'} "
                        f"{o['src']}->{o['dst']} tag {o['tag']}"
                        for _i, o in sorted(remaining.items())
                    ]
                    raise UnmatchedP2PError(
                        f"rank {me}: no counterpart/slot progress for "
                        f"{missing} within {timeout_ms} ms — the peer(s) "
                        "never issued the matching op(s)")
                else:
                    _time.sleep(0.005)
    except Exception:
        # roll back the FIFO tags of every unexecuted op so a failed probe
        # (or mismatch) cannot desync later matched transfers
        for _i, o in sorted(remaining.items(), key=lambda kv: -kv[1]["tag"]):
            key = (o["gk"], o["src"], o["dst"])
            if _p2p_dir_tags.get(key, 0) == o["tag"] + 1:
                _p2p_dir_tags[key] = o["tag"]
        raise
    return tasks


def batch_isend_irecv(p2p_op_list):
    """Reference communication/batch_isend_irecv.py.  On the SPMD path p2p
    is ppermute inside programs; eagerly, multi-controller batches execute
    as a sequence of pairwise ppermute executables.

    With a rendezvous store (launch / init_parallel_env) the pattern is
    STORE-COORDINATED: arbitrary — including four-directions-style —
    schedules where ranks post differently-ordered, partially-overlapping
    op lists resolve to a canonical global order, and a genuinely missing
    counterpart raises UnmatchedP2PError instead of hanging.  Without a
    store, the original matched-pairs contract applies (both endpoints
    post the same transfer set, canonical sorted-pair order)."""
    me = jax.process_index()

    if any((p.group.nranks if p.group is not None else jax.process_count()) > 1
           for p in p2p_op_list):
        from paddle_tpu.distributed.communication.watchdog import get_rendezvous_store

        store = get_rendezvous_store()
        if store is not None:
            return _coordinated_batch(p2p_op_list, store, me)

    annotated = []
    for p in p2p_op_list:
        world = p.group.nranks if p.group is not None else jax.process_count()
        if world == 1:
            annotated.append((None, False, p))
            continue
        is_send = _is_send(p.op)
        pair = (me, p.peer) if is_send else (p.peer, me)
        annotated.append((tuple(sorted(pair)) + (pair[0],), is_send, p))
    tasks = []
    for key, is_send, p in sorted(
        annotated, key=lambda kp: (kp[0] is not None, kp[0] or ())
    ):
        if key is None:
            tasks.append(Task(p.tensor._value if hasattr(p.tensor, "_value") else p.tensor))
            continue
        if p.group is not None:
            pg = p.group
        else:
            from paddle_tpu.distributed.communication.ops import _process_group_for

            pg = _process_group_for(None)  # cached world ring
        if is_send:
            tasks.append(pg.send(p.tensor, p.peer))
        else:
            tasks.append(pg.recv(p.tensor, p.peer))
    return tasks
