"""ProcessGroup: eager cross-process collectives with the async-Task API.

Reference: paddle/fluid/distributed/collective/process_group.h:47 (async
ops returning event-backed Tasks), process_group_nccl.h:37 (per-device comm
streams, ring ids), nccl_comm_context.h.

TPU-native redesign (SURVEY.md §7 "ProcessGroup-on-XLA"): there are no
comm streams to manage — an eager collective outside any compiled program
is itself a tiny COMPILED COLLECTIVE EXECUTABLE.  For a group spanning the
multi-controller world (jax.distributed initialized, one process per host):

  local value --make_array(global mesh over the ring)--> global jax.Array
  --cached jitted psum/all_gather/...--> async result --Task

The executable is cached per (op, shape, dtype, ring) — the KernelKey-style
dispatch cache the survey calls for — so repeated small collectives (global
norm terms, scalar broadcasts) pay dispatch, not compilation.  XLA runs the
collective asynchronously; Task.wait blocks on the result buffer (watchdog-
guarded), Task.is_completed polls it — the event-backed Task contract.

Single-process groups short-circuit (the reference's nranks==1 fast path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ProcessGroup", "P2POp", "batch_isend_irecv"]


class Task:
    """Async collective handle (reference process_group.h Task)."""

    def __init__(self, result=None, group=None, name="collective"):
        self._result = result
        self._group = group
        self._name = name

    def wait(self, timeout=None):
        if self._result is not None and hasattr(self._result, "block_until_ready"):
            from paddle_tpu.distributed.communication.watchdog import comm_watch

            with comm_watch(self._name, group=self._group, timeout=timeout):
                self._result.block_until_ready()
        return True

    def is_completed(self):
        r = self._result
        if r is None or not hasattr(r, "is_ready"):
            return True
        return bool(r.is_ready())

    def result(self):
        return self._result


class ProcessGroup:
    """A ring of PROCESSES (multi-controller) issuing compiled collectives."""

    def __init__(self, ranks=None, ring_id=0, name=None):
        self.ranks = list(ranks) if ranks is not None else list(range(jax.process_count()))
        self.ring_id = ring_id
        self._name = name or f"pg_{ring_id}"
        self._cache: dict = {}  # (op, shape, dtype) -> compiled fn
        self._mesh = None

    @property
    def nranks(self):
        return len(self.ranks)

    size = nranks

    def rank(self):
        return self.ranks.index(jax.process_index()) if jax.process_index() in self.ranks else -1

    # ------------------------------------------------------------- plumbing
    def _ring_mesh(self):
        """One mesh axis over the ring's processes (one device per process:
        the process-leader device, matching one-NCCL-rank-per-proc)."""
        if self._mesh is None:
            devs = []
            for r in self.ranks:
                cands = [d for d in jax.devices() if d.process_index == r]
                if not cands:
                    raise RuntimeError(f"process {r} has no devices visible")
                devs.append(cands[0])
            self._mesh = jax.sharding.Mesh(np.asarray(devs), ("ring",))
        return self._mesh

    def _global(self, value):
        """Lift the local value to a ring-global array [nranks, ...]."""
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self._ring_mesh()
        sharding = NamedSharding(mesh, PartitionSpec("ring"))
        local = jnp.asarray(value)[None]
        return jax.make_array_from_single_device_arrays(
            (self.nranks,) + tuple(local.shape[1:]), sharding, [local]
        )

    def _compiled(self, op_name, builder, value):
        key = (op_name, tuple(value.shape), str(value.dtype), tuple(self.ranks))
        fn = self._cache.get(key)
        if fn is None:
            fn = builder()
            self._cache[key] = fn
        return fn

    def cache_size(self):
        return len(self._cache)

    def _run(self, op_name, value, body, out_spec):
        """Compile-and-cache a shard_map collective over the ring."""
        from jax.sharding import NamedSharding, PartitionSpec

        if self.nranks == 1:
            return value, None
        mesh = self._ring_mesh()

        def builder():
            from jax import shard_map

            f = shard_map(
                body, mesh=mesh, in_specs=PartitionSpec("ring"),
                out_specs=out_spec, axis_names={"ring"},
            )
            return jax.jit(f)

        fn = self._compiled(op_name, builder, value)
        garr = self._global(value)
        out = fn(garr)
        return out, out

    # ----------------------------------------------------------- collectives
    def allreduce(self, tensor, op="sum"):
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "allreduce")
        red = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin, "avg": lax.pmean}[op]

        def body(x):  # x: [1, ...] local slice
            return red(x, "ring")

        out, _ = self._run(f"allreduce_{op}", v, body, PartitionSpec("ring"))
        # every slice holds the reduction; read the local one
        local = out.addressable_shards[0].data[0]
        if isinstance(tensor, Tensor):
            tensor._bind(local)
        return Task(local, self, "allreduce")

    def allgather(self, tensor):
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v[None], self, "allgather")

        def body(x):
            return lax.all_gather(x[0], "ring")

        out, _ = self._run("allgather", v, body, PartitionSpec("ring"))
        return Task(out.addressable_shards[0].data, self, "allgather")

    def broadcast(self, tensor, src=0):
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "broadcast")
        src_idx = self.ranks.index(src)

        def body(x):
            return lax.all_gather(x[0], "ring")[src_idx][None]

        out, _ = self._run(f"broadcast_{src_idx}", v, body, PartitionSpec("ring"))
        local = out.addressable_shards[0].data[0]
        if isinstance(tensor, Tensor):
            tensor._bind(local)
        return Task(local, self, "broadcast")

    def reduce_scatter(self, tensor, op="sum"):
        """Input [nranks*chunk, ...] per rank; each keeps its reduced chunk."""
        from paddle_tpu._core.tensor import Tensor
        from jax import lax
        from jax.sharding import PartitionSpec

        v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if self.nranks == 1:
            return Task(v, self, "reduce_scatter")

        def body(x):
            return lax.psum_scatter(x[0], "ring", scatter_dimension=0, tiled=True)[None]

        out, _ = self._run("reduce_scatter", v, body, PartitionSpec("ring"))
        return Task(out.addressable_shards[0].data[0], self, "reduce_scatter")

    def barrier(self):
        t = self.allreduce(jnp.zeros((), jnp.int32))
        t.wait()
        return t


class P2POp:
    """Batched p2p descriptor (reference batch_isend_irecv)."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op  # "isend" | "irecv"
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Reference communication/batch_isend_irecv.py.  On the SPMD path p2p is
    ppermute inside programs; eagerly, world-1 is a no-op and multi-host p2p
    maps to a ring ppermute executable per batch (future work beyond the
    single-host image).  Returns Tasks."""
    tasks = []
    for p in p2p_op_list:
        world = p.group.nranks if p.group is not None else jax.process_count()
        if world != 1:
            raise NotImplementedError(
                "eager multi-host batch_isend_irecv: use the SPMD pipeline "
                "engine (ppermute) or ProcessGroup collectives"
            )
        tasks.append(Task(p.tensor._value if hasattr(p.tensor, "_value") else p.tensor))
    return tasks
