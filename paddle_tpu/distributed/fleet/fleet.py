"""Fleet facade.

Reference: python/paddle/distributed/fleet/fleet.py (init :167,
distributed_model via model.py:32, distributed_optimizer :1307) configured by
DistributedStrategy (base/distributed_strategy.py over
distributed_strategy.proto).

TPU-native: fleet.init builds the hybrid topology as ONE device mesh
(HCG axes → mesh axes) and sets it as the default ProcessMesh.
distributed_model/distributed_optimizer annotate rather than wrap:
parallelism executes when the train step is compiled (ShardedTrainStep /
fleet.make_train_step), where GSPMD+shard_map place every collective the
reference's meta_parallel engines issue imperatively.
"""

from __future__ import annotations

import numpy as np
import jax

from .base.topology import CommunicateTopology, HybridCommunicateGroup

__all__ = [
    "DistributedStrategy",
    "init",
    "is_initialized",
    "distributed_model",
    "distributed_optimizer",
    "get_hybrid_communicate_group",
    "make_train_step",
    "worker_index",
    "worker_num",
]


class DistributedStrategy:
    """Strategy knobs (reference: distributed_strategy.proto).  Unknown
    attributes are accepted and stored, mirroring the protobuf's breadth."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.fuse_all_reduce_ops = True
        self.find_unused_parameters = False

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class _FleetEnv:
    strategy: DistributedStrategy | None = None
    topology: CommunicateTopology | None = None
    hcg: HybridCommunicateGroup | None = None
    mesh = None
    initialized = False


_env = _FleetEnv()


def init(role_maker=None, is_collective: bool = True, strategy: DistributedStrategy | None = None, log_level="INFO"):
    """Initialize fleet (reference fleet.py:167): derive the hybrid topology
    from the strategy and the visible device count, build HCG + default mesh."""
    from paddle_tpu.distributed.auto_parallel import set_mesh
    from paddle_tpu.distributed.env import init_parallel_env

    init_parallel_env()
    _env.role_maker = role_maker
    if role_maker is not None and not is_collective and role_maker.is_server():
        # PS mode server: no collective topology to build
        _env.strategy = strategy or DistributedStrategy()
        _env.initialized = True
        return None
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    n_dev = jax.device_count()
    degrees = {
        "data": int(hc.get("dp_degree", 1)),
        "pipe": int(hc.get("pp_degree", 1)),
        "sharding": int(hc.get("sharding_degree", 1)),
        "sep": int(hc.get("sep_degree", 1)),
        "model": int(hc.get("mp_degree", 1)),
    }
    known = int(np.prod([d for d in degrees.values() if d > 0]))
    if degrees["data"] == -1 or (known < n_dev and degrees["data"] == 1):
        others = int(np.prod([degrees[k] for k in ("pipe", "sharding", "sep", "model")]))
        degrees["data"] = max(1, n_dev // others)
    topo = CommunicateTopology(
        ["data", "pipe", "sharding", "sep", "model"],
        [degrees[k] for k in ("data", "pipe", "sharding", "sep", "model")],
    )
    _env.strategy = strategy
    _env.topology = topo
    _env.hcg = HybridCommunicateGroup(topo, global_rank=0)
    _env.mesh = _env.hcg.as_process_mesh()
    set_mesh(_env.mesh)
    _env.initialized = True
    return None


def is_initialized() -> bool:
    return _env.initialized


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _env.hcg


def fleet_env():
    return _env


def worker_index() -> int:
    return jax.process_index()


def worker_num() -> int:
    return jax.process_count()


def distributed_model(model):
    """Annotate a model for the fleet topology (reference model.py:32 picks
    the meta_parallel engine).  pp conversion requires the model to expose a
    pipelineable trunk (see PipelineStack); TP layers (mpu) self-annotate at
    construction under the fleet mesh."""
    if not _env.initialized:
        raise RuntimeError("call fleet.init() first")
    model._fleet_mesh = _env.mesh
    return model


class HybridParallelOptimizer:
    """Optimizer wrapper (reference dygraph_optimizer/
    hybrid_parallel_optimizer.py:270).  Grad clipping across mesh axes is
    global by construction (grads are global arrays); sharding stages are
    recorded for the compiled step."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, *a, **k):
        return self._inner_opt.minimize(*a, **k)


def distributed_optimizer(optimizer, strategy=None):
    if not _env.initialized:
        raise RuntimeError("call fleet.init() first")
    return HybridParallelOptimizer(optimizer, hcg=_env.hcg, strategy=strategy or _env.strategy)


def make_train_step(model, optimizer, loss_fn, scaler=None, num_microbatches=None):
    """Compile the hybrid train step for the fleet topology: batch sharded
    over data axes (dp and sharding), zero stage from strategy.sharding."""
    from jax.sharding import PartitionSpec

    from paddle_tpu.distributed.sharded_step import ShardedTrainStep

    if not _env.initialized:
        raise RuntimeError("call fleet.init() first")
    mesh = _env.mesh
    data_axes = tuple(ax for ax in ("dp", "sharding") if ax in mesh.dim_names)
    batch_spec = PartitionSpec(data_axes if len(data_axes) > 1 else (data_axes[0] if data_axes else None))
    zero = 0
    if _env.strategy is not None and _env.strategy.sharding:
        zero = int(_env.strategy.sharding_configs.get("stage", 1))
    elif "sharding" in mesh.dim_names:
        zero = 1
    inner = optimizer._inner_opt if isinstance(optimizer, HybridParallelOptimizer) else optimizer
    dp_axis = "dp" if "dp" in mesh.dim_names else ("sharding" if "sharding" in mesh.dim_names else "dp")
    return ShardedTrainStep(
        model, inner, loss_fn, mesh, batch_spec=batch_spec, zero_stage=zero, dp_axis=dp_axis, scaler=scaler
    )


# ---------------------------------------------------------------- PS mode
# Reference: fleet's parameter-server runtime (fleet.init(role_maker) with
# PaddleCloudRoleMaker, runtime/the_one_ps.py init_server/run_server/
# init_worker/stop_worker).  TPU-native scope: the PS tier serves host
# sparse-embedding tables (distributed/ps/, scope decision documented
# there); the role surface below wires fleet's API onto it.


class PaddleCloudRoleMaker:
    """Env-var driven role assignment (reference
    fleet/base/role_maker.py PaddleCloudRoleMaker): TRAINING_ROLE=TRAINER|
    PSERVER, PADDLE_TRAINER_ID / PADDLE_PSERVER_ID."""

    def __init__(self, is_collective=False, **kwargs):
        import os

        self._is_collective = is_collective
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._index = int(
            os.environ.get("PADDLE_TRAINER_ID", os.environ.get("PADDLE_PSERVER_ID", "0"))
        )

    def is_server(self):
        return self._role == "PSERVER"

    def is_worker(self):
        return self._role == "TRAINER"

    def role_index(self):
        return self._index


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=False, current_id=0, role="TRAINER", **kwargs):
        self._is_collective = is_collective
        self._role = role.upper()
        self._index = int(current_id)


def _role():
    return getattr(_env, "role_maker", None)


def is_server() -> bool:
    r = _role()
    return bool(r and r.is_server())


def is_worker() -> bool:
    r = _role()
    return r.is_worker() if r else True


def init_server(*model_dirs, **kwargs):
    """Start serving registered SparseTables over rpc (the_one_ps
    init_server analog).  Tables register via PsServer.register_table."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PsServer

    name = kwargs.get("name", f"pserver{_role().role_index() if _role() else 0}")
    if not rpc.get_all_worker_infos():
        rpc.init_rpc(
            name,
            rank=kwargs.get("rank"),
            world_size=kwargs.get("world_size"),
            master_endpoint=kwargs.get("master_endpoint"),
        )
    _env.ps_server = PsServer()
    return _env.ps_server


def run_server():
    """Block serving rpc requests until shutdown (reference run_server)."""
    import time

    while getattr(_env, "ps_server", None) is not None:
        time.sleep(0.2)


def init_worker(scopes=None):
    """Worker-side PS setup: nothing to prefetch on the TPU path (pull
    happens per batch through SparseEmbedding)."""
    return None


def stop_worker():
    from paddle_tpu.distributed import rpc

    _env.ps_server = None
    try:
        rpc.shutdown()
    except Exception:
        pass


class Role:
    """reference: python/paddle/distributed/fleet/base/role_maker.py Role."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class UtilBase:
    """Cross-rank small-data helpers (reference:
    python/paddle/distributed/fleet/base/util_factory.py UtilBase): host
    object collectives over the rendezvous/communication layer."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from paddle_tpu.distributed import ReduceOp, all_reduce as _ar
        from paddle_tpu._core.tensor import Tensor

        ops = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX, "min": ReduceOp.MIN}
        if mode not in ops:
            raise ValueError(f"all_reduce mode must be sum/max/min, got {mode!r}")
        t = Tensor(np.asarray(input))
        out = _ar(t, op=ops[mode])
        return np.asarray(out._value if isinstance(out, Tensor) else t._value)

    def barrier(self, comm_world="worker"):
        from paddle_tpu.distributed import barrier as _b

        _b()

    def all_gather(self, input, comm_world="worker"):
        """Gather each rank's host object: world-1 returns [input]; multi-
        process exchanges pickles through the rendezvous store."""
        import pickle

        from paddle_tpu.distributed import get_rank, get_world_size
        from paddle_tpu.distributed.communication.watchdog import get_rendezvous_store

        world = get_world_size()
        if world == 1:
            return [input]
        store = get_rendezvous_store()
        if store is None:
            raise RuntimeError("util.all_gather needs a rendezvous store outside world-1")
        rank = get_rank()
        self._ag_seq = getattr(self, "_ag_seq", 0) + 1
        store.set(f"util_ag/{self._ag_seq}/{rank}", pickle.dumps(input))
        return [pickle.loads(store.get(f"util_ag/{self._ag_seq}/{r}")) for r in range(world)]

    def get_file_shard(self, files):
        """Split a file list across workers, remainder to the first trainers
        (reference util_factory.get_file_shard: every worker gets floor or
        floor+1 files, none idle)."""
        from paddle_tpu.distributed import get_rank, get_world_size

        w, r = get_world_size(), get_rank()
        base, rem = divmod(len(files), w)
        start = r * base + min(r, rem)
        return files[start : start + base + (1 if r < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        from paddle_tpu.distributed import get_rank

        if get_rank() == int(rank_id):
            print(message)


util = UtilBase()


class MultiSlotDataGenerator:
    """PS-mode data generator (reference:
    python/paddle/distributed/fleet/data_generator/data_generator.py):
    subclass generate_sample(line) yielding [(slot_name, [ids...]), ...];
    run_from_stdin/run_from_files feed the PS dataset pipeline."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError("subclass must implement generate_sample")

    def set_batch(self, batch_size):
        self._batch = int(batch_size)

    def _format(self, sample):
        # MultiSlot text protocol: "slots_num slot_len v0 v1 ... " per slot
        parts = []
        for _, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_files(self, files, output_fn=print):
        for path in files:
            with open(path) as f:
                for line in f:
                    gen = self.generate_sample(line.rstrip("\n"))
                    for sample in (gen() if callable(gen) else gen):
                        output_fn(self._format(sample))

    def run_from_stdin(self):
        import sys

        for line in sys.stdin:
            gen = self.generate_sample(line.rstrip("\n"))
            for sample in (gen() if callable(gen) else gen):
                print(self._format(sample))


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant (values emitted verbatim)."""


__all__ += ["Role", "UtilBase", "util", "MultiSlotDataGenerator", "MultiSlotStringDataGenerator"]


class Fleet:
    """The Fleet singleton class (reference: fleet.py:167 class Fleet).
    This build implements fleet as module-level functions over _FleetEnv;
    the class view binds the same operations for scripts that instantiate
    or type-check paddle.distributed.fleet.Fleet."""

    def init(self, role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
        return init(role_maker, is_collective, strategy)

    is_initialized = staticmethod(is_initialized)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_server = staticmethod(is_server)
    is_worker = staticmethod(is_worker)
    init_server = staticmethod(init_server)
    init_worker = staticmethod(init_worker)
    run_server = staticmethod(run_server)
    stop_worker = staticmethod(stop_worker)

    @property
    def util(self):
        return util


__all__ += ["Fleet"]
