from .pipeline import PipelineStack  # noqa: F401
