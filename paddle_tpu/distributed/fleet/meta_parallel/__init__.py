from .pipeline import PipelineStack, pipeline_parallel, segment_layers  # noqa: F401
from .schedules import (  # noqa: F401
    Costs,
    Schedule,
    available_schedules,
    get_schedule,
    pipeline_stats,
    register_schedule,
    simulate,
)
from .segment_parallel import SegmentParallel, sep_attention, split_inputs_sequence_dim  # noqa: F401
