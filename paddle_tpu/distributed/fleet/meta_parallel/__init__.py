from .pipeline import PipelineStack, segment_layers  # noqa: F401
from .segment_parallel import SegmentParallel, sep_attention, split_inputs_sequence_dim  # noqa: F401
