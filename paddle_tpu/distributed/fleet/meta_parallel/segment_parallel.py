"""Segment (sequence/context) parallel engine — the SEP axis.

Reference: python/paddle/distributed/fleet/meta_parallel/segment_parallel.py:26
(thin engine broadcasting params over the sep group; attention-side handling
left to model code).  The TPU build goes further (SURVEY.md §5 explicitly
allows exceeding): `sep_attention` gives model code real sequence-parallel
attention — ring (ppermute K/V rotation) or Ulysses (all-to-all head
resharding) — and `SegmentParallel` wraps a Layer so its inputs/activations
are sequence-sharded over the 'sep' mesh axis inside the fleet train step.
"""

from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu.tensor._ops_common import apply, ensure_tensor
from paddle_tpu.distributed.communication.ops import _axis_for, current_axis_scope
from paddle_tpu.ops.ring_attention import ring_attention, ulysses_attention

__all__ = ["SegmentParallel", "sep_attention", "split_inputs_sequence_dim"]


def sep_attention(q, k, v, *, causal=True, scale=None, group=None, mode="ring"):
    """Sequence-parallel attention on Tensors [B, S_local, N, H].

    Inside an SPMD region with the sep axis in scope this runs ring/Ulysses
    attention over the axis; at world 1 it falls back to local flash
    attention (same signature as F.scaled_dot_product_attention).
    """
    q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
    if group is not None:
        from paddle_tpu.distributed.communication.ops import _single_axis

        ax = _single_axis(_axis_for(group), "sep_attention")
    else:
        # group=None means the SEP axis specifically, never the whole world
        ax = current_axis_scope().get("sep")
    if ax is None:
        from paddle_tpu.nn.functional.attention import scaled_dot_product_attention

        return scaled_dot_product_attention(q, k, v, is_causal=causal)

    fn = ring_attention if mode == "ring" else ulysses_attention
    return apply(
        f"sep_attention_{mode}",
        lambda qv, kv, vv: fn(qv, kv, vv, ax, causal=causal, scale=scale),
        q,
        k,
        v,
    )


def split_inputs_sequence_dim(inputs, rank, degree, seq_axis=1):
    """Static pre-shard of a batch along the sequence dim (reference
    fleet/utils/hybrid_parallel_util.py)."""
    t = ensure_tensor(inputs)
    s = t.shape[seq_axis]
    assert s % degree == 0
    chunk = s // degree
    idx = [slice(None)] * len(t.shape)
    idx[seq_axis] = slice(rank * chunk, (rank + 1) * chunk)
    return t[tuple(idx)]


class SegmentParallel(nn.Layer):
    """Engine wrapper parity with the reference: holds the model, exposes
    sequence-shard helpers; param broadcast is a no-op under SPMD (params are
    replicated over 'sep' by sharding spec, not by explicit broadcast)."""

    def __init__(self, layers, hcg=None, **kwargs):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    @property
    def sep_degree(self):
        if self._hcg is None:
            return 1
        return self._hcg.get_sep_parallel_world_size()

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
