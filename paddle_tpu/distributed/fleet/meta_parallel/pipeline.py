"""Pipeline parallelism — SPMD pipeline engine over a 'pp' mesh axis.

Reference counterpart: fleet PipelineLayer partitioning
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:237,
SegmentLayers:92) + the 1F1B runtime engine
(meta_parallel/pipeline_parallel.py:648 train_batch, :431
forward_backward_pipeline) + p2p send/recv
(pp_utils/p2p_communication.py:313,512) + the schedule pass family
(python/paddle/distributed/passes/pipeline_scheduler_pass.py:47-566 —
FThenB / 1F1B variants as data, not code).

TPU-native redesign: instead of per-rank processes exchanging activations
over NCCL p2p with a hand-written fwd/bwd interleave, the pipeline is ONE
SPMD program:

- The N identical blocks' parameters are stacked [n_stages, layers_per_stage,
  ...] and sharded over the 'pp' mesh axis — each stage's weights live on its
  own devices, like the reference's per-rank layer partition.
- The microbatch rotation is a single `lax.scan` over T = M + S - 1 ticks
  inside shard_map (manual over 'pp' only; dp/mp stay GSPMD-auto); per tick
  each stage computes its chunk and the boundary activation hops one stage
  via lax.ppermute on ICI — the p2p_communication.py equivalent.  scan keeps
  compile time independent of the microbatch count (the unrolled round-1
  engine retraced every tick).
- Schedules are DATA, selecting the autodiff memory profile:
  * "1F1B" (default): each tick's stage computation is wrapped in
    jax.checkpoint, so the forward stores only the per-tick boundary
    activations; the backward then recomputes one stage-tick and
    backpropagates it, tick by tick in reverse — the bounded-activation
    1F1B profile (peak residency: boundary tensors + ONE stage's
    activations), without hand-writing the backward schedule.
  * "FThenB": no per-tick checkpoint; XLA stores every stage's internals for
    the whole forward (GPipe memory, fewest recompute FLOPs).
  The bubble fraction (S-1)/(M+S-1) is schedule-intrinsic and identical for
  both — raise num_microbatches to shrink it.
- Activation recompute per layer (use_recompute=True, jax.checkpoint inside
  the stage) replaces the reference's RecomputeFunction inside stages.

Constraints (same as the reference's uniform SegmentLayers path): all blocks
structurally identical, block output shape == input shape, and
len(blocks) % pp_degree == 0.  num_microbatches may exceed the stage count
(steady-state 1F1B, reference pipeline_parallel.py:431) — it must divide the
batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu._core.autograd import apply, no_grad
from paddle_tpu._core.tensor import Parameter, Tensor
from paddle_tpu.nn import Layer


def _pvary(x, axes):
    # jax>=0.9 renames pvary -> pcast(..., to='varying'); support both
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return lax.pvary(x, axes)

__all__ = ["PipelineStack"]

_SCHEDULES = ("1F1B", "FThenB", "VPP")


class PipelineStack(Layer):
    """Replaces a LayerList of identical blocks with a pipelined stack.

    schedule="VPP" (interleaved virtual pipeline, reference
    PipelineParallelWithInterleave pipeline_parallel.py:890 + the VPP
    scheduler pass): each device owns `num_virtual_stages` non-contiguous
    layer chunks (chunk c on device c % S) and the rotation is a circular
    token ring — each device carries ONE (microbatch, chunk) token per tick,
    device 0 injects a fresh microbatch whenever a completed token returns.
    T = M*v + S - 1 ticks, so the bubble shrinks v-fold to
    (S-1)/(M*v + S-1) at the cost of v x more ppermute hops — the VPP
    trade exactly."""

    def __init__(self, blocks, mesh, pp_axis: str = "pp", num_microbatches=None,
                 use_recompute: bool = False, schedule: str = "1F1B",
                 num_virtual_stages: int = 1):
        super().__init__()
        from paddle_tpu.distributed.auto_parallel import ProcessMesh
        from paddle_tpu.distributed.auto_parallel.api import placements_to_spec

        if schedule not in _SCHEDULES:
            raise ValueError(f"schedule must be one of {_SCHEDULES}, got {schedule!r}")
        blocks = list(blocks)
        if not blocks:
            raise ValueError("PipelineStack needs at least one block")
        if not isinstance(mesh, ProcessMesh):
            mesh = ProcessMesh(mesh)
        self._mesh = mesh
        self._pp_axis = pp_axis
        self._n_stages = mesh.get_dim_size(pp_axis)
        self._n_layers = len(blocks)
        self._n_virtual = int(num_virtual_stages) if schedule == "VPP" else 1
        if self._n_virtual < 1:
            raise ValueError("num_virtual_stages must be >= 1")
        n_chunks = self._n_stages * self._n_virtual
        if self._n_layers % n_chunks != 0:
            raise ValueError(
                f"{self._n_layers} blocks not divisible into {n_chunks} "
                f"chunks ({self._n_stages} stages x {self._n_virtual} virtual)"
            )
        self._layers_per_stage = self._n_layers // self._n_stages
        if num_microbatches is not None and num_microbatches < 1:
            raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")
        self._num_microbatches = num_microbatches
        self._use_recompute = use_recompute
        self._schedule = schedule

        # Template block: bypass Layer registration so its params stay out of
        # this layer's state_dict (they become dead storage bound over by the
        # traced stage function).
        object.__setattr__(self, "_template", blocks[0])
        tpl_state = blocks[0].state_dict()
        self._keys = list(tpl_state.keys())
        self._tpl_tensors = [tpl_state[k] for k in self._keys]

        states = [b.state_dict() for b in blocks]
        for st in states:
            if list(st.keys()) != self._keys:
                raise ValueError("pipeline blocks must be structurally identical")

        jmesh = mesh.jax_mesh
        S, Lps, v = self._n_stages, self._layers_per_stage, self._n_virtual
        # VPP block order: device d holds chunks {d, S+d, 2S+d, ...}; its
        # local [v, Lpc] layout maps (j, i) -> block (j*S + d)*Lpc + i.
        # v == 1 reduces to the contiguous [S, Lps] split.
        lpc = Lps // v
        order = [
            (j * S + d) * lpc + i
            for d in range(S)
            for j in range(v)
            for i in range(lpc)
        ]
        for key, tpl in zip(self._keys, self._tpl_tensors):
            vals = [states[b][key]._value for b in order]
            stacked = jnp.stack(vals).reshape((S, Lps) + vals[0].shape)
            if getattr(tpl, "process_mesh", None) is not None and tpl.placements:
                block_spec = list(placements_to_spec(tpl.process_mesh, tpl.placements))
            else:
                block_spec = []
            spec = PartitionSpec(pp_axis, None, *block_spec)
            stacked = jax.device_put(stacked, NamedSharding(jmesh, spec))
            p = Parameter(stacked, trainable=not tpl.stop_gradient)
            p.stop_gradient = tpl.stop_gradient
            self.add_parameter(self._mangle(key), p)

    @staticmethod
    def _mangle(key: str) -> str:
        return "stacked__" + key.replace(".", "__")

    def stacked_parameters(self):
        return [self._parameters[self._mangle(k)] for k in self._keys]

    def bubble_fraction(self, num_microbatches=None) -> float:
        """Pipeline bubble (S-1)/(M*v + S-1) — reference pipeline math; the
        interleaved factor v divides the bubble (pipeline_parallel.py:890)."""
        m = num_microbatches or self._num_microbatches or self._n_stages
        return (self._n_stages - 1) / (m * self._n_virtual + self._n_stages - 1)

    # ------------------------------------------------------------------ fwd
    def forward(self, h, *bcast):
        S = self._n_stages
        M = self._num_microbatches or S
        B = h.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        bcast_t = [b for b in bcast if isinstance(b, Tensor)]
        self._bcast_template = [b if isinstance(b, Tensor) else None for b in bcast]

        x = h.reshape([M, B // M] + list(h.shape[1:]))
        out = apply(
            "pipeline_stack",
            self._make_fn(M),
            *self.stacked_parameters(),
            x,
            *bcast_t,
        )
        return out.reshape([B] + list(h.shape[1:]))

    def _make_fn(self, M):
        S = self._n_stages
        Lps = self._layers_per_stage
        pp = self._pp_axis
        jmesh = self._mesh.jax_mesh
        n_keys = len(self._keys)
        template = self._template
        tpl_tensors = self._tpl_tensors
        bcast_template = self._bcast_template
        use_recompute = self._use_recompute
        per_tick_remat = self._schedule in ("1F1B", "VPP")
        n_virtual = self._n_virtual
        lpc = Lps // n_virtual

        def pipe_vpp(stacked, x, bcast_vals, stage):
            """Circular token ring (see class docstring): each device carries
            one (microbatch m, chunk c) token; device 0 injects when a
            completed (c == V) token returns.  T = M*v + S - 1 ticks."""
            V = S * n_virtual
            ring = [(i, (i + 1) % S) for i in range(S)]
            wlocal = [w[0] for w in stacked]  # [v*lpc, ...] local chunks

            def chunk_fn(chunk_local, h_val):
                # run the lpc layers of local chunk `chunk_local` (traced idx)
                for i in range(lpc):
                    li = chunk_local * lpc + i
                    params_i = [
                        lax.dynamic_index_in_dim(w, li, 0, keepdims=False)
                        for w in wlocal
                    ]
                    h_val = layer_call(params_i, h_val, bcast_vals)
                return h_val

            if per_tick_remat:
                chunk_fn = jax.checkpoint(chunk_fn)

            # the last microbatch is injected at ((M-1)//S)*V + (M-1)%S and
            # computes its final chunk V-1 ticks later; for M % S == 0 this
            # reduces to M*v + S - 1
            T = ((M - 1) // S) * V + ((M - 1) % S) + V

            def tick(carry, t):
                h, m_idx, c_idx, next_m, out = carry
                dead = c_idx >= V
                inject = jnp.logical_and(jnp.logical_and(stage == 0, dead), next_m < M)
                m_new = jnp.where(inject, next_m, m_idx)
                c_new = jnp.where(inject, 0, c_idx)
                h_in = jnp.where(
                    inject,
                    lax.dynamic_index_in_dim(x, jnp.clip(next_m, 0, M - 1), 0, keepdims=False),
                    h,
                )
                next_m2 = jnp.where(inject, next_m + 1, next_m)
                active = c_new < V
                chunk_local = jnp.clip(c_new // S, 0, n_virtual - 1)
                y = chunk_fn(chunk_local, h_in)
                y = jnp.where(active, y, h_in)
                c_after = jnp.where(active, c_new + 1, c_new)
                done_now = jnp.logical_and(active, c_after == V)
                m_out = jnp.clip(m_new, 0, M - 1)
                cur = lax.dynamic_index_in_dim(out, m_out, 0, keepdims=False)
                out = lax.dynamic_update_index_in_dim(
                    out, jnp.where(done_now, y, cur), m_out, 0
                )
                h_next = lax.ppermute(y, pp, ring)
                m_next = lax.ppermute(m_new, pp, ring)
                c_next = lax.ppermute(c_after, pp, ring)
                return (h_next, m_next, c_next, next_m2, out), None

            carry0 = (
                _pvary(jnp.zeros_like(x[0]), (pp,)),
                _pvary(jnp.asarray(-1, jnp.int32), (pp,)),
                _pvary(jnp.asarray(V, jnp.int32), (pp,)),  # dead: inject
                _pvary(jnp.asarray(0, jnp.int32), (pp,)),
                _pvary(jnp.zeros_like(x), (pp,)),
            )
            (_, _, _, _, out), _ = lax.scan(tick, carry0, jnp.arange(T, dtype=jnp.int32))
            return lax.psum(out, pp)

        def layer_call(params_i, h_val, bcast_vals):
            originals = [t._value for t in tpl_tensors]
            try:
                for t, v in zip(tpl_tensors, params_i):
                    t._bind(v)
                it = iter(bcast_vals)
                args = [Tensor(next(it)) if b is not None else None for b in bcast_template]
                with no_grad():
                    out = template(Tensor(h_val), *args)
                return out._value if isinstance(out, Tensor) else out
            finally:
                for t, v in zip(tpl_tensors, originals):
                    t._bind(v)

        def pipe(*vals):
            stacked = vals[:n_keys]           # each [1, Lps, ...] local
            x = vals[n_keys]                  # [M, mb, ...] (replicated over pp)
            bcast_vals = vals[n_keys + 1:]
            stage = lax.axis_index(pp)
            wlocal = [w[0] for w in stacked]  # [Lps, ...]

            def stage_fn(h_val):
                for i in range(Lps):
                    params_i = [w[i] for w in wlocal]
                    call = (lambda ps, hv: layer_call(ps, hv, bcast_vals))
                    if use_recompute:
                        call = jax.checkpoint(call)
                    h_val = call(params_i, h_val)
                return h_val

            if per_tick_remat:
                stage_fn = jax.checkpoint(stage_fn)

            if n_virtual > 1:
                return pipe_vpp(stacked, x, bcast_vals, stage)

            T = M + S - 1
            ring = [(i, (i + 1) % S) for i in range(S)]

            def tick(carry, t):
                buf, out = carry
                # stage 0 feeds microbatch t (last one repeated through the
                # drain ticks — the classic warmup/drain bubble); others eat
                # the boundary activation that just hopped in on the ring.
                m_in = jnp.clip(t, 0, M - 1)
                inp = jnp.where(stage == 0, lax.dynamic_index_in_dim(x, m_in, 0, keepdims=False), buf)
                y = stage_fn(inp)
                # last stage owns microbatch t-(S-1)'s output
                m_out = jnp.clip(t - (S - 1), 0, M - 1)
                cur = lax.dynamic_index_in_dim(out, m_out, 0, keepdims=False)
                write = jnp.logical_and(stage == S - 1, t >= S - 1)
                out = lax.dynamic_update_index_in_dim(
                    out, jnp.where(write, y, cur), m_out, 0
                )
                buf = lax.ppermute(y, pp, ring)
                return (buf, out), None

            # carries become pp-varying inside the loop; type them so upfront
            carry0 = (
                _pvary(jnp.zeros_like(x[0]), (pp,)),
                _pvary(jnp.zeros_like(x), (pp,)),
            )
            (_, out), _ = lax.scan(tick, carry0, jnp.arange(T, dtype=jnp.int32))
            # outputs live on the last stage; psum replicates them over pp
            # (non-last stages contributed zeros)
            return lax.psum(out, pp)

        def fn(*vals):
            in_specs = tuple(PartitionSpec(pp) for _ in range(n_keys)) + tuple(
                PartitionSpec() for _ in range(len(vals) - n_keys)
            )
            return shard_map(
                pipe,
                mesh=jmesh,
                in_specs=in_specs,
                out_specs=PartitionSpec(),
                axis_names={pp},
            )(*vals)

        return fn
