"""Pipeline parallelism — SPMD pipeline engine over a 'pp' mesh axis.

Reference counterpart: fleet PipelineLayer partitioning
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:237,
SegmentLayers:92) + the 1F1B runtime engine
(meta_parallel/pipeline_parallel.py:648 train_batch, :431
forward_backward_pipeline) + p2p send/recv
(pp_utils/p2p_communication.py:313,512) + the schedule pass family
(python/paddle/distributed/passes/pipeline_scheduler_pass.py:47-566 —
FThenB / 1F1B variants as data, not code).

TPU-native redesign: instead of per-rank processes exchanging activations
over NCCL p2p with a hand-written fwd/bwd interleave, the pipeline is ONE
SPMD program:

- The N identical blocks' parameters are stacked [n_stages, layers_per_stage,
  ...] and sharded over the 'pp' mesh axis — each stage's weights live on its
  own devices, like the reference's per-rank layer partition.
- The microbatch rotation is a single `lax.scan` over T = M + S - 1 ticks
  inside shard_map (manual over 'pp' only; dp/mp stay GSPMD-auto); per tick
  each stage computes its chunk and the boundary activation hops one stage
  via lax.ppermute on ICI — the p2p_communication.py equivalent.  scan keeps
  compile time independent of the microbatch count (the unrolled round-1
  engine retraced every tick).
- Schedules are DATA, selecting the autodiff memory profile:
  * "1F1B" (default): each tick's stage computation is wrapped in
    jax.checkpoint, so the forward stores only the per-tick boundary
    activations; the backward then recomputes one stage-tick and
    backpropagates it, tick by tick in reverse — the bounded-activation
    1F1B profile (peak residency: boundary tensors + ONE stage's
    activations), without hand-writing the backward schedule.
  * "FThenB": no per-tick checkpoint; XLA stores every stage's internals for
    the whole forward (GPipe memory, fewest recompute FLOPs).
  The bubble fraction (S-1)/(M+S-1) is schedule-intrinsic and identical for
  both — raise num_microbatches to shrink it.
- Activation recompute per layer (use_recompute=True, jax.checkpoint inside
  the stage) replaces the reference's RecomputeFunction inside stages.

Constraints (same as the reference's uniform SegmentLayers path): all TRUNK
blocks structurally identical, block output shape == input shape, and
len(blocks) % pp_degree == 0.  num_microbatches may exceed the stage count
(steady-state 1F1B, reference pipeline_parallel.py:431) — it must divide the
batch.

Non-uniform stages (reference SegmentLayers:92 puts embedding on the first
stage and the head on the last): `first_stage` / `last_stage` layers ride
the same SPMD program guarded by `lax.cond(stage == 0 / S-1, ...)`, so the
embedding runs only where stage 0's devices execute and the head only on the
last stage — the cond keeps the FLOPs off the other stages at runtime.  The
ring still carries the uniform trunk activation; the input buffer holds the
raw model input (e.g. token ids) and the output buffer the head's output
(e.g. logits), whose shapes may both differ from the trunk activation.
Cost-weighted trunk segmentation (SegmentLayers seg_method="uniform"/
param-weighted) degenerates to uniform here because trunk blocks are
structurally identical — the heterogeneity LLMs actually have (embedding/
head) is exactly what first_stage/last_stage carry; `segment_layers` below
keeps the reference's cut algorithm available for planner parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu._core.autograd import apply, no_grad
from paddle_tpu._core.tensor import Parameter, Tensor
from paddle_tpu.nn import Layer


def _pvary(x, axes):
    # jax>=0.9 renames pvary -> pcast(..., to='varying'); support both.
    # jax<0.6 has neither AND no varying-manual-axes type system — there
    # shard_map(check_rep=False) accepts replicated values directly, so the
    # cast is correctly a no-op.
    # Idempotent: values already varying over the axes pass through — but
    # only that case; any other ValueError (bad axis name, bad to=) raises.
    try:
        if hasattr(lax, "pcast"):
            return lax.pcast(x, axes, to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(x, axes)
        return x
    except ValueError as e:
        if "from=varying" in str(e) or "already" in str(e):
            return x
        raise

__all__ = ["PipelineStack", "segment_layers", "pipeline_parallel"]

# "VPP" is engine-structural (circular token ring); the rest live in the
# schedules registry (fleet/meta_parallel/schedules.py) — ZB-H1 selects the
# split-backward scan pair below.
_SCHEDULES = ("1F1B", "FThenB", "VPP", "ZB-H1")


def segment_layers(weights, num_stages, method: str = "uniform"):
    """Cut a heterogeneous layer list into pipeline stages (reference
    SegmentLayers, fleet pp_layers.py:92): returns num_stages+1 cut points.

    method="uniform": equal layer counts (remainder spread to the front);
    method="param" (reference seg_method="layer:..."/parameter-weighted):
    balance the per-stage sum of `weights` (e.g. parameter counts) greedily
    along the prefix-sum, the reference's segment_parts strategy."""
    n = len(weights)
    if num_stages < 1 or n < num_stages:
        raise ValueError(f"cannot cut {n} layers into {num_stages} stages")
    if method == "uniform":
        base, rem = divmod(n, num_stages)
        cuts = [0]
        for s in range(num_stages):
            cuts.append(cuts[-1] + base + (1 if s < rem else 0))
        return cuts
    if method == "param":
        total = float(sum(weights))
        prefix = [0.0]
        for w in weights:
            prefix.append(prefix[-1] + float(w))
        cuts = [0]
        for s in range(1, num_stages):
            target = total * s / num_stages
            # closest prefix point that keeps at least one layer per stage
            lo, hi = cuts[-1] + 1, n - (num_stages - s)
            best = min(range(lo, hi + 1), key=lambda i: abs(prefix[i] - target))
            cuts.append(best)
        cuts.append(n)
        return cuts
    raise ValueError(f"unknown segment method {method!r}")


class PipelineStack(Layer):
    """Replaces a LayerList of identical blocks with a pipelined stack.

    schedule="VPP" (interleaved virtual pipeline, reference
    PipelineParallelWithInterleave pipeline_parallel.py:890 + the VPP
    scheduler pass): each device owns `num_virtual_stages` non-contiguous
    layer chunks (chunk c on device c % S) and the rotation is a circular
    token ring — each device carries ONE (microbatch, chunk) token per tick,
    device 0 injects a fresh microbatch whenever a completed token returns.
    T = M*v + S - 1 ticks, so the bubble shrinks v-fold to
    (S-1)/(M*v + S-1) at the cost of v x more ppermute hops — the VPP
    trade exactly."""

    def __init__(self, blocks, mesh, pp_axis: str = "pp", num_microbatches=None,
                 use_recompute: bool = False, schedule: str = None,
                 num_virtual_stages: int = 1, first_stage=None, last_stage=None):
        super().__init__()
        from paddle_tpu.distributed.auto_parallel import ProcessMesh
        from paddle_tpu.distributed.auto_parallel.api import placements_to_spec

        from . import schedules as _schedules

        # schedule=None follows FLAGS_pipeline_schedule; the schedules-module
        # flag listener re-resolves such stacks on set_flags (and drops their
        # cached built steps) — the FLAGS_decode_chunk contract.
        self._follow_flag = schedule is None
        if schedule is None:
            schedule = _schedules.resolve_schedule_flag()
        if schedule not in _SCHEDULES:
            raise ValueError(f"schedule must be one of {_SCHEDULES}, got {schedule!r}")
        self._fn_cache = {}
        _schedules.register_stack(self)
        blocks = list(blocks)
        if not blocks:
            raise ValueError("PipelineStack needs at least one block")
        if not isinstance(mesh, ProcessMesh):
            mesh = ProcessMesh(mesh)
        self._mesh = mesh
        self._pp_axis = pp_axis
        self._n_stages = mesh.get_dim_size(pp_axis)
        self._n_layers = len(blocks)
        self._n_virtual = int(num_virtual_stages) if schedule == "VPP" else 1
        if self._n_virtual < 1:
            raise ValueError("num_virtual_stages must be >= 1")
        n_chunks = self._n_stages * self._n_virtual
        if self._n_layers % n_chunks != 0:
            raise ValueError(
                f"{self._n_layers} blocks not divisible into {n_chunks} "
                f"chunks ({self._n_stages} stages x {self._n_virtual} virtual)"
            )
        self._layers_per_stage = self._n_layers // self._n_stages
        if num_microbatches is not None and num_microbatches < 1:
            raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")
        self._num_microbatches = num_microbatches
        self._use_recompute = use_recompute
        self._schedule = schedule

        # first/last stage extras (embedding / head): NOT registered as
        # sublayers — their params stay registered wherever the caller keeps
        # them (so optimizers see each exactly once); forward() threads the
        # same Tensor objects through the tape, which routes their grads.
        object.__setattr__(self, "_first", first_stage)
        object.__setattr__(self, "_last", last_stage)
        self._first_tensors = list(first_stage.state_dict().values()) if first_stage else []
        self._last_tensors = list(last_stage.state_dict().values()) if last_stage else []

        # Template block: bypass Layer registration so its params stay out of
        # this layer's state_dict (they become dead storage bound over by the
        # traced stage function).
        object.__setattr__(self, "_template", blocks[0])
        tpl_state = blocks[0].state_dict()
        self._keys = list(tpl_state.keys())
        self._tpl_tensors = [tpl_state[k] for k in self._keys]

        states = [b.state_dict() for b in blocks]
        for st in states:
            if list(st.keys()) != self._keys:
                raise ValueError("pipeline blocks must be structurally identical")

        jmesh = mesh.jax_mesh
        S, Lps, v = self._n_stages, self._layers_per_stage, self._n_virtual
        # VPP block order: device d holds chunks {d, S+d, 2S+d, ...}; its
        # local [v, Lpc] layout maps (j, i) -> block (j*S + d)*Lpc + i.
        # v == 1 reduces to the contiguous [S, Lps] split.
        lpc = Lps // v
        order = [
            (j * S + d) * lpc + i
            for d in range(S)
            for j in range(v)
            for i in range(lpc)
        ]
        for key, tpl in zip(self._keys, self._tpl_tensors):
            vals = [states[b][key]._value for b in order]
            stacked = jnp.stack(vals).reshape((S, Lps) + vals[0].shape)
            if getattr(tpl, "process_mesh", None) is not None and tpl.placements:
                block_spec = list(placements_to_spec(tpl.process_mesh, tpl.placements))
            else:
                block_spec = []
            spec = PartitionSpec(pp_axis, None, *block_spec)
            stacked = jax.device_put(stacked, NamedSharding(jmesh, spec))
            p = Parameter(stacked, trainable=not tpl.stop_gradient)
            p.stop_gradient = tpl.stop_gradient
            self.add_parameter(self._mangle(key), p)

    @staticmethod
    def _mangle(key: str) -> str:
        return "stacked__" + key.replace(".", "__")

    def stacked_parameters(self):
        return [self._parameters[self._mangle(k)] for k in self._keys]

    def bubble_fraction(self, num_microbatches=None) -> float:
        """Pipeline bubble (S-1)/(M*v + S-1) — reference pipeline math; the
        interleaved factor v divides the bubble (pipeline_parallel.py:890)."""
        m = num_microbatches or self._num_microbatches or self._n_stages
        return (self._n_stages - 1) / (m * self._n_virtual + self._n_stages - 1)

    def _edge_call(self, layer, tensors):
        """Traced call of a first/last stage layer: bind the incoming traced
        param values over the layer's tensors, run it, restore."""
        def call(h_val, vals):
            originals = [t._value for t in tensors]
            try:
                for t, v in zip(tensors, vals):
                    t._bind(v)
                with no_grad():
                    out = layer(Tensor(h_val))
                return out._value if isinstance(out, Tensor) else out
            finally:
                for t, v in zip(tensors, originals):
                    t._bind(v)
        return call

    # ------------------------------------------------------------------ fwd
    def forward(self, h, *bcast):
        S = self._n_stages
        M = self._num_microbatches or S
        B = h.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        bcast_t = [b for b in bcast if isinstance(b, Tensor)]
        self._bcast_template = [b if isinstance(b, Tensor) else None for b in bcast]

        # trunk-activation and output shapes per microbatch: the first/last
        # stage layers may change both (ids -> hidden, hidden -> logits).
        # The probes run layers through the funnel, so under static capture
        # they MUST suspend recording (same rule as program.record's op
        # bodies) — otherwise eval_shape tracers get baked into the program.
        from paddle_tpu.static.program import suspend_capture

        mb_struct = jax.ShapeDtypeStruct((B // M,) + tuple(int(s) for s in h.shape[1:]), h._value.dtype)
        with suspend_capture():
            if self._first is not None:
                call = self._edge_call(self._first, self._first_tensors)
                vals = [t._value for t in self._first_tensors]
                h_struct = jax.eval_shape(lambda hv: call(hv, vals), mb_struct)
            else:
                h_struct = mb_struct
            if self._last is not None:
                call = self._edge_call(self._last, self._last_tensors)
                vals = [t._value for t in self._last_tensors]
                out_struct = jax.eval_shape(lambda hv: call(hv, vals), h_struct)
            else:
                out_struct = h_struct
        self._h_struct, self._out_struct = h_struct, out_struct

        x = h.reshape([M, B // M] + list(h.shape[1:]))
        args = (*self.stacked_parameters(), *self._first_tensors,
                *self._last_tensors, x, *bcast_t)
        self._maybe_mesh_lint(M, args)
        from . import schedules as _schedules

        _schedules._count_program(self._schedule, self._n_stages, M,
                                  self._n_virtual)
        out = apply("pipeline_stack", self._get_fn(M), *args)
        return out.reshape([B] + list(out_struct.shape[1:]))

    # ------------------------------------------------- schedule management
    def set_schedule(self, schedule: str):
        """Select a schedule explicitly (pipeline_scheduler pass face);
        drops cached built steps so the next forward traces the new one."""
        if schedule not in _SCHEDULES:
            raise ValueError(f"schedule must be one of {_SCHEDULES}, got {schedule!r}")
        if self._n_virtual > 1 and schedule != "VPP":
            # VPP stacks interleave the stacked weights in chunk order
            # ((j*S + d)*lpc + i); every other engine reads them
            # contiguously — switching would silently compose blocks in a
            # permuted global order.
            raise ValueError(
                f"stack was built interleaved (num_virtual_stages="
                f"{self._n_virtual}); its weights are stacked in VPP chunk "
                f"order — rebuild the stack to use schedule {schedule!r}")
        self._follow_flag = False
        if schedule != self._schedule:
            self._schedule = schedule
            self._fn_cache.clear()
            self._mesh_linted_at = None

    def _on_schedule_flag_change(self):
        """schedules-module flag listener: FLAGS_pipeline_schedule changed."""
        if not getattr(self, "_follow_flag", False):
            return
        from . import schedules as _schedules

        new = _schedules.resolve_schedule_flag()
        if new != self._schedule:
            self._schedule = new
            self._fn_cache.clear()
            self._mesh_linted_at = None

    def _get_fn(self, M):
        """Cached built step per (schedule, M, probed shapes, bcast mask) —
        what the flags listener invalidates.  Scan bodies are defined inside
        the traced callables, so a cached fn is safe to re-trace under a
        different jit (docs/SCAN_LAYERS.md body-identity rule)."""
        struct_key = tuple(
            (tuple(s.shape), str(s.dtype)) if s is not None else None
            for s in (getattr(self, "_h_struct", None),
                      getattr(self, "_out_struct", None)))
        key = (self._schedule, M, struct_key,
               tuple(b is not None for b in self._bcast_template))
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = self._fn_cache[key] = self._make_fn(M)
        return fn

    def _maybe_mesh_lint(self, M, args):
        """FLAGS_verify_sharding hook: abstractly walk the assembled
        pipeline program (ring ppermutes, the stage-0/last-stage conds,
        the final psum) against the mesh BEFORE the first dispatch — a
        ring built for the wrong stage count or a mis-axised hop is a
        named error here, not an 8-device rendezvous hang.  Once per
        (stack, microbatch count); the trace is abstract only."""
        from paddle_tpu._core import flags as _flags

        if not _flags.flag("FLAGS_verify_sharding"):
            return
        if getattr(self, "_mesh_linted_at", None) == M:
            return
        from paddle_tpu.static.mesh_lint import MeshLinter, _finish

        avals = [jax.ShapeDtypeStruct(t._value.shape, t._value.dtype)
                 for t in args]
        linter = MeshLinter(mesh=self._mesh)
        # Every built-in schedule lints clean as-is: the edge layers' VJP
        # transpose-psums are hoisted OUT of the stage-predicated conds by
        # construction (see pipe()'s pp-varying casts), so any
        # conditional-collective that DOES surface here is a user block's
        # own data-dependent collective — the real deadlock class.
        fn = self._get_fn(M)
        violations = linter.lint_callable(
            fn, *avals, site=f"pipeline_stack[{self._schedule}]")
        if self._schedule == "ZB-H1":
            # The split backward is a hand-scheduled scan with its own ring
            # ppermutes and grad psums — the new deadlock surface.  Lint the
            # whole vjp program too (jax autodiff never sees it at runtime:
            # the custom_vjp bwd IS the program being checked here).
            out_struct = getattr(self, "_out_struct", None)
            mb_shape = tuple(out_struct.shape) if out_struct is not None \
                else tuple(avals[-1].shape[1:])
            mb_dtype = out_struct.dtype if out_struct is not None \
                else avals[-1].dtype
            cot = jax.ShapeDtypeStruct((M,) + mb_shape, mb_dtype)

            def grad_prog(*a):
                ins, ct = a[:-1], a[-1]
                diff = [i for i, v in enumerate(ins)
                        if jnp.issubdtype(v.dtype, jnp.inexact)]
                dset = set(diff)

                def g(*dv):
                    it = iter(dv)
                    return fn(*[next(it) if i in dset else ins[i]
                                for i in range(len(ins))])

                _, vjp = jax.vjp(g, *[ins[i] for i in diff])
                return vjp(ct)

            violations += linter.lint_callable(
                grad_prog, *avals, cot,
                site=f"pipeline_stack[{self._schedule}].backward")
        _finish(violations, "Mesh lint failed (PipelineStack)",
                raise_on_error=True)
        self._mesh_linted_at = M

    def _make_fn(self, M):
        if self._schedule == "ZB-H1":
            return self._make_zb_fn(M)
        S = self._n_stages
        Lps = self._layers_per_stage
        pp = self._pp_axis
        jmesh = self._mesh.jax_mesh
        n_keys = len(self._keys)
        template = self._template
        tpl_tensors = self._tpl_tensors
        bcast_template = self._bcast_template
        use_recompute = self._use_recompute
        per_tick_remat = self._schedule in ("1F1B", "VPP")
        n_virtual = self._n_virtual
        lpc = Lps // n_virtual
        nf, nl = len(self._first_tensors), len(self._last_tensors)
        # set by forward(); None when _make_fn is driven directly (tests,
        # structure inspection) — then trunk-in == trunk-out == x's shape
        h_struct = getattr(self, "_h_struct", None)
        out_struct = getattr(self, "_out_struct", None)
        first_call = (
            self._edge_call(self._first, self._first_tensors) if self._first else None
        )
        last_call = (
            self._edge_call(self._last, self._last_tensors) if self._last else None
        )

        def pipe_vpp(stacked, x, bcast_vals, stage, first_vals=(), last_vals=()):
            """Circular token ring (see class docstring): each device carries
            one (microbatch m, chunk c) token; device 0 injects when a
            completed (c == V) token returns.  T = M*v + S - 1 ticks."""
            V = S * n_virtual
            ring = [(i, (i + 1) % S) for i in range(S)]
            wlocal = [w[0] for w in stacked]  # [v*lpc, ...] local chunks

            def chunk_fn(chunk_local, h_val):
                # run the lpc layers of local chunk `chunk_local` (traced idx)
                for i in range(lpc):
                    li = chunk_local * lpc + i
                    params_i = [
                        lax.dynamic_index_in_dim(w, li, 0, keepdims=False)
                        for w in wlocal
                    ]
                    h_val = layer_call(params_i, h_val, bcast_vals)
                return h_val

            if per_tick_remat:
                chunk_fn = jax.checkpoint(chunk_fn)

            # the last microbatch is injected at ((M-1)//S)*V + (M-1)%S and
            # computes its final chunk V-1 ticks later; for M % S == 0 this
            # reduces to M*v + S - 1
            T = ((M - 1) // S) * V + ((M - 1) % S) + V

            def tick(carry, t):
                h, m_idx, c_idx, next_m, out = carry
                dead = c_idx >= V
                inject = jnp.logical_and(jnp.logical_and(stage == 0, dead), next_m < M)
                m_new = jnp.where(inject, next_m, m_idx)
                c_new = jnp.where(inject, 0, c_idx)
                raw = lax.dynamic_index_in_dim(x, jnp.clip(next_m, 0, M - 1), 0, keepdims=False)
                if first_call is not None:
                    # pre-cast cond inputs to pp-varying (see non-VPP note)
                    fed = lax.cond(
                        inject,
                        lambda r: first_call(r, first_vals),
                        lambda r: _pvary(jnp.zeros(h_struct.shape, h_struct.dtype), (pp,)),
                        _pvary(raw, (pp,)),
                    )
                else:
                    fed = raw
                h_in = jnp.where(inject, fed, h)
                next_m2 = jnp.where(inject, next_m + 1, next_m)
                active = c_new < V
                chunk_local = jnp.clip(c_new // S, 0, n_virtual - 1)
                y = chunk_fn(chunk_local, h_in)
                y = jnp.where(active, y, h_in)
                c_after = jnp.where(active, c_new + 1, c_new)
                done_now = jnp.logical_and(active, c_after == V)
                m_out = jnp.clip(m_new, 0, M - 1)
                cur = lax.dynamic_index_in_dim(out, m_out, 0, keepdims=False)
                if last_call is not None:
                    val = lax.cond(
                        done_now,
                        lambda yy: last_call(yy, last_vals),
                        lambda yy: _pvary(jnp.zeros(out_struct.shape, out_struct.dtype), (pp,)),
                        y,
                    )
                else:
                    val = y
                out = lax.dynamic_update_index_in_dim(
                    out, jnp.where(done_now, val, cur), m_out, 0
                )
                h_next = lax.ppermute(y, pp, ring)
                m_next = lax.ppermute(m_new, pp, ring)
                c_next = lax.ppermute(c_after, pp, ring)
                return (h_next, m_next, c_next, next_m2, out), None

            zeros_h = (jnp.zeros(h_struct.shape, h_struct.dtype)
                       if h_struct is not None else jnp.zeros_like(x[0]))
            zeros_out = (jnp.zeros((M,) + tuple(out_struct.shape), out_struct.dtype)
                         if out_struct is not None else jnp.zeros_like(x))
            carry0 = (
                _pvary(zeros_h, (pp,)),
                _pvary(jnp.asarray(-1, jnp.int32), (pp,)),
                _pvary(jnp.asarray(V, jnp.int32), (pp,)),  # dead: inject
                _pvary(jnp.asarray(0, jnp.int32), (pp,)),
                _pvary(zeros_out, (pp,)),
            )
            (_, _, _, _, out), _ = lax.scan(tick, carry0, jnp.arange(T, dtype=jnp.int32))
            return lax.psum(out, pp)

        def layer_call(params_i, h_val, bcast_vals):
            originals = [t._value for t in tpl_tensors]
            try:
                for t, v in zip(tpl_tensors, params_i):
                    t._bind(v)
                it = iter(bcast_vals)
                args = [Tensor(next(it)) if b is not None else None for b in bcast_template]
                with no_grad():
                    out = template(Tensor(h_val), *args)
                return out._value if isinstance(out, Tensor) else out
            finally:
                for t, v in zip(tpl_tensors, originals):
                    t._bind(v)

        def pipe(*vals):
            stacked = vals[:n_keys]           # each [1, Lps, ...] local
            # pp-varying casts up front: their transpose-psums then run
            # uniformly on every device, outside any stage-predicated cond
            first_vals = [_pvary(v, (pp,)) for v in vals[n_keys:n_keys + nf]]
            last_vals = [_pvary(v, (pp,)) for v in vals[n_keys + nf:n_keys + nf + nl]]
            x = vals[n_keys + nf + nl]        # [M, mb, ...] (replicated over pp)
            bcast_vals = vals[n_keys + nf + nl + 1:]
            stage = lax.axis_index(pp)
            wlocal = [w[0] for w in stacked]  # [Lps, ...]

            def stage_fn(h_val):
                for i in range(Lps):
                    params_i = [w[i] for w in wlocal]
                    call = (lambda ps, hv: layer_call(ps, hv, bcast_vals))
                    if use_recompute:
                        call = jax.checkpoint(call)
                    h_val = call(params_i, h_val)
                return h_val

            if per_tick_remat:
                stage_fn = jax.checkpoint(stage_fn)

            if n_virtual > 1:
                return pipe_vpp(stacked, x, bcast_vals, stage, first_vals, last_vals)

            T = M + S - 1
            ring = [(i, (i + 1) % S) for i in range(S)]

            def tick(carry, t):
                buf, out = carry
                # stage 0 feeds microbatch t (last one repeated through the
                # drain ticks — the classic warmup/drain bubble); others eat
                # the boundary activation that just hopped in on the ring.
                m_in = jnp.clip(t, 0, M - 1)
                raw = lax.dynamic_index_in_dim(x, m_in, 0, keepdims=False)
                if first_call is not None:
                    # cond keeps the embedding off stages != 0 at runtime.
                    # EVERYTHING entering the cond is pre-cast to pp-varying
                    # (params at the top of pipe, raw here): an unvarying
                    # value used inside a stage-predicated branch would get
                    # its transpose-psum(pp) placed inside the branch, which
                    # only one stage executes -> collective deadlock.
                    fed = lax.cond(
                        stage == 0,
                        lambda r: first_call(r, first_vals),
                        lambda r: _pvary(jnp.zeros(h_struct.shape, h_struct.dtype), (pp,)),
                        _pvary(raw, (pp,)),
                    )
                else:
                    fed = raw
                inp = jnp.where(stage == 0, fed, buf)
                y = stage_fn(inp)
                # last stage owns microbatch t-(S-1)'s output
                m_out = jnp.clip(t - (S - 1), 0, M - 1)
                cur = lax.dynamic_index_in_dim(out, m_out, 0, keepdims=False)
                write = jnp.logical_and(stage == S - 1, t >= S - 1)
                if last_call is not None:
                    # head (e.g. lm-head matmul) only runs on write ticks of
                    # the last stage
                    val = lax.cond(
                        write,
                        lambda yy: last_call(yy, last_vals),
                        lambda yy: _pvary(jnp.zeros(out_struct.shape, out_struct.dtype), (pp,)),
                        y,
                    )
                else:
                    val = y
                out = lax.dynamic_update_index_in_dim(
                    out, jnp.where(write, val, cur), m_out, 0
                )
                buf = lax.ppermute(y, pp, ring)
                return (buf, out), None

            # carries become pp-varying inside the loop; type them so upfront
            zeros_h = (jnp.zeros(h_struct.shape, h_struct.dtype)
                       if h_struct is not None else jnp.zeros_like(x[0]))
            zeros_out = (jnp.zeros((M,) + tuple(out_struct.shape), out_struct.dtype)
                         if out_struct is not None else jnp.zeros_like(x))
            carry0 = (
                _pvary(zeros_h, (pp,)),
                _pvary(zeros_out, (pp,)),
            )
            (_, out), _ = lax.scan(tick, carry0, jnp.arange(T, dtype=jnp.int32))
            # outputs live on the last stage; psum replicates them over pp
            # (non-last stages contributed zeros)
            return lax.psum(out, pp)

        def fn(*vals):
            in_specs = tuple(PartitionSpec(pp) for _ in range(n_keys)) + tuple(
                PartitionSpec() for _ in range(len(vals) - n_keys)
            )
            return shard_map(
                pipe,
                mesh=jmesh,
                in_specs=in_specs,
                out_specs=PartitionSpec(),
                axis_names={pp},
            )(*vals)

        return fn

    # ----------------------------------------------------- ZB split backward
    def _make_zb_fn(self, M):
        """The zero-bubble engine pair: a forward scan that stores ONLY the
        per-tick boundary activations, and a hand-scheduled backward scan
        (jax.custom_vjp) consuming the schedule's engine plan — at backward
        tick r it runs the grad-INPUT pass of forward tick b_tick[r] (the B
        slot: recompute the tick under jax.vjp w.r.t. the boundary input,
        reverse-ppermute the cotangent to the upstream stage) and the
        DEFERRED grad-WEIGHT pass of forward tick w_tick[r] (the W slot:
        vjp w.r.t. the stage/edge parameters from the stored cotangents).
        Grad-weight deferral changes only the accumulation order, so grads
        match the fused 1F1B backward within jit-reassociation tolerance.

        Assumes deterministic stage fns (the recompute replays the forward;
        fresh per-call RNG — dropout — would diverge between the fwd trace
        and the bwd recompute; same limitation as any uncoordinated remat).
        """
        import numpy as np

        from . import schedules as _schedules

        S = self._n_stages
        Lps = self._layers_per_stage
        pp = self._pp_axis
        jmesh = self._mesh.jax_mesh
        n_keys = len(self._keys)
        template = self._template
        tpl_tensors = self._tpl_tensors
        bcast_template = self._bcast_template
        use_recompute = self._use_recompute
        nf, nl = len(self._first_tensors), len(self._last_tensors)
        h_struct = getattr(self, "_h_struct", None)
        out_struct = getattr(self, "_out_struct", None)
        first_call = (
            self._edge_call(self._first, self._first_tensors) if self._first else None
        )
        last_call = (
            self._edge_call(self._last, self._last_tensors) if self._last else None
        )

        plan = _schedules.get_schedule(self._schedule).engine_plan(S, M)
        T, TB = plan["T"], plan["TB"]
        b_tick = jnp.asarray(plan["b_tick"], jnp.int32)
        w_tick = jnp.asarray(plan["w_tick"], jnp.int32)
        ring = [(i, (i + 1) % S) for i in range(S)]
        ring_rev = [(i, (i - 1) % S) for i in range(S)]

        def layer_call(params_i, h_val, bcast_vals):
            originals = [t._value for t in tpl_tensors]
            try:
                for t, v in zip(tpl_tensors, params_i):
                    t._bind(v)
                it = iter(bcast_vals)
                args = [Tensor(next(it)) if b is not None else None
                        for b in bcast_template]
                with no_grad():
                    out = template(Tensor(h_val), *args)
                return out._value if isinstance(out, Tensor) else out
            finally:
                for t, v in zip(tpl_tensors, originals):
                    t._bind(v)

        def stage_fn(wlocal, h_val, bcast_vals):
            for i in range(Lps):
                params_i = [w[i] for w in wlocal]
                call = (lambda ps, hv: layer_call(ps, hv, bcast_vals))
                if use_recompute:
                    call = jax.checkpoint(call)
                h_val = call(params_i, h_val)
            return h_val

        def _idx(arr, i):
            return lax.dynamic_index_in_dim(arr, i, 0, keepdims=False)

        def _upd(arr, v, i):
            return lax.dynamic_update_index_in_dim(arr, v, i, 0)

        def tick_core(wlocal, first_vals, last_vals, buf, raw, bcast_vals,
                      t, stage):
            """One forward tick WITHOUT the ring hop / out write: returns
            (y, val).  val is the candidate output-buffer value — the head
            output under the write-tick cond, else y; the caller (and the
            cotangent extraction in the backward) masks it by `write`."""
            if first_call is not None:
                fed = lax.cond(
                    stage == 0,
                    lambda r: first_call(r, first_vals),
                    lambda r: _pvary(jnp.zeros(h_struct.shape, h_struct.dtype), (pp,)),
                    _pvary(raw, (pp,)),
                )
            else:
                fed = raw
            inp = jnp.where(stage == 0, fed, buf)
            y = stage_fn(wlocal, inp, bcast_vals)
            if last_call is not None:
                write = jnp.logical_and(stage == S - 1, t >= S - 1)
                val = lax.cond(
                    write,
                    lambda yy: last_call(yy, last_vals),
                    lambda yy: _pvary(jnp.zeros(out_struct.shape, out_struct.dtype), (pp,)),
                    y,
                )
            else:
                val = y
            return y, val

        def _unpack(vals):
            stacked = vals[:n_keys]
            first_vals = tuple(_pvary(v, (pp,)) for v in vals[n_keys:n_keys + nf])
            last_vals = tuple(_pvary(v, (pp,))
                              for v in vals[n_keys + nf:n_keys + nf + nl])
            x = vals[n_keys + nf + nl]
            bcast_vals = tuple(vals[n_keys + nf + nl + 1:])
            return stacked, first_vals, last_vals, x, bcast_vals

        def _zeros_h(x):
            return (jnp.zeros(h_struct.shape, h_struct.dtype)
                    if h_struct is not None else jnp.zeros_like(x[0]))

        def _zeros_out(x):
            return (jnp.zeros((M,) + tuple(out_struct.shape), out_struct.dtype)
                    if out_struct is not None else jnp.zeros_like(x))

        def pipe_fwd(*vals):
            stacked, first_vals, last_vals, x, bcast_vals = _unpack(vals)
            stage = lax.axis_index(pp)
            wlocal = [w[0] for w in stacked]

            def tick(carry, t):
                buf, out = carry
                raw = _idx(x, jnp.clip(t, 0, M - 1))
                y, val = tick_core(wlocal, first_vals, last_vals, buf, raw,
                                   bcast_vals, t, stage)
                m_out = jnp.clip(t - (S - 1), 0, M - 1)
                write = jnp.logical_and(stage == S - 1, t >= S - 1)
                cur = _idx(out, m_out)
                out = _upd(out, jnp.where(write, val, cur), m_out)
                buf_next = lax.ppermute(y, pp, ring)
                # ys: the tick's INPUT boundary — the only stored residual
                return (buf_next, out), buf

            carry0 = (_pvary(_zeros_h(x), (pp,)), _pvary(_zeros_out(x), (pp,)))
            (_, out), buf_store = lax.scan(tick, carry0,
                                           jnp.arange(T, dtype=jnp.int32))
            return lax.psum(out, pp), buf_store[None]  # [1, T, mb...] local

        def pipe_bwd(*args):
            vals, store, g_out_in = args[:-2], args[-2], args[-1]
            stacked, first_vals, last_vals, x, bcast_vals = _unpack(vals)
            stage = lax.axis_index(pp)
            wlocal = [w[0] for w in stacked]
            buf_store = store[0]  # [T, mb...]
            x_diff = jnp.issubdtype(x.dtype, jnp.inexact)
            bc_diff = tuple(jnp.issubdtype(b.dtype, jnp.inexact)
                            for b in bcast_vals)

            zh = _zeros_h(x)
            zv = (jnp.zeros(out_struct.shape, out_struct.dtype)
                  if out_struct is not None else zh)

            def btick(carry, r):
                (g_buf, g_out, g_x, g_bc, gp, gf, gl, gy_buf, gv_buf) = carry
                # ---------------- B slot: grad-input of forward tick t
                t = _idx(b_tick, r)
                bv = t >= 0
                tc = jnp.clip(t, 0, T - 1)
                # cotangent of this tick's y arriving on the reversed ring
                g_y = jnp.where(bv, lax.ppermute(g_buf, pp, ring_rev), 0)
                m_out = jnp.clip(tc - (S - 1), 0, M - 1)
                write = jnp.logical_and(
                    jnp.logical_and(stage == S - 1, tc >= S - 1), bv)
                cur = _idx(g_out, m_out)
                g_val = jnp.where(write, cur, jnp.zeros_like(cur))
                g_out = _upd(g_out, jnp.where(write, jnp.zeros_like(cur), cur),
                             m_out)
                buf_t = _idx(buf_store, tc)
                m_in = jnp.clip(tc, 0, M - 1)
                raw_t = _idx(x, m_in)

                diff_b = (buf_t,) + ((raw_t,) if x_diff else ()) + tuple(
                    b for b, d in zip(bcast_vals, bc_diff) if d)

                def f_b(*db):
                    it = iter(db)
                    buf_ = next(it)
                    raw_ = next(it) if x_diff else raw_t
                    bc_ = tuple(next(it) if d else b
                                for b, d in zip(bcast_vals, bc_diff))
                    return tick_core(wlocal, first_vals, last_vals, buf_,
                                     raw_, bc_, tc, stage)

                _, vjp_b = jax.vjp(f_b, *diff_b)
                gb = list(vjp_b((g_y, g_val)))
                g_buf_new = gb.pop(0)
                if x_diff:
                    g_raw = gb.pop(0)
                    g_x = _upd(g_x, _idx(g_x, m_in) + g_raw, m_in)
                g_bc = tuple(
                    (acc + gb.pop(0)) if d else acc
                    for acc, d in zip(g_bc, bc_diff))
                # store this tick's output cotangents for the deferred W
                gy_buf = _upd(gy_buf, jnp.where(bv, g_y, _idx(gy_buf, tc)), tc)
                gv_buf = _upd(gv_buf, jnp.where(bv, g_val, _idx(gv_buf, tc)), tc)

                # ---------------- W slot: deferred grad-weight of tick tw
                tw = _idx(w_tick, r)
                wv = tw >= 0
                twc = jnp.clip(tw, 0, T - 1)
                gy_w = jnp.where(wv, _idx(gy_buf, twc), 0)
                gv_w = jnp.where(wv, _idx(gv_buf, twc), 0)
                buf_w = _idx(buf_store, twc)
                raw_w = _idx(x, jnp.clip(twc, 0, M - 1))

                def f_w(wl, fv, lv):
                    return tick_core(wl, fv, lv, buf_w, raw_w, bcast_vals,
                                     twc, stage)

                _, vjp_w = jax.vjp(f_w, wlocal, first_vals, last_vals)
                gw, gfv, glv = vjp_w((gy_w, gv_w))
                gp = [a + b for a, b in zip(gp, gw)]
                gf = tuple(a + b for a, b in zip(gf, gfv))
                gl = tuple(a + b for a, b in zip(gl, glv))
                return (g_buf_new, g_out, g_x, g_bc, gp, gf, gl,
                        gy_buf, gv_buf), None

            carry0 = (
                _pvary(jnp.zeros_like(zh), (pp,)),          # g_buf
                _pvary(g_out_in, (pp,)),                    # psum transpose
                jnp.zeros_like(x) if x_diff else jnp.zeros((), x.dtype),
                tuple(jnp.zeros_like(b) if d else jnp.zeros((), b.dtype)
                      for b, d in zip(bcast_vals, bc_diff)),
                [jnp.zeros_like(w) for w in wlocal],
                tuple(jnp.zeros_like(v) for v in first_vals),
                tuple(jnp.zeros_like(v) for v in last_vals),
                _pvary(jnp.zeros((T,) + zh.shape, zh.dtype), (pp,)),
                _pvary(jnp.zeros((T,) + zv.shape, zv.dtype), (pp,)),
            )
            (g_buf, g_out, g_x, g_bc, gp, gf, gl, _, _), _ = lax.scan(
                btick, carry0, jnp.arange(TB, dtype=jnp.int32))

            # replicated inputs: sum the per-stage contributions uniformly
            # (outside any stage-predicated cond — the mesh-lint contract)
            out = [g[None] for g in gp]                      # [1, Lps, ...]
            out += [lax.psum(g, pp) for g in gf]
            out += [lax.psum(g, pp) for g in gl]
            if x_diff:
                out.append(lax.psum(g_x, pp))
            out += [lax.psum(g, pp) for g, d in zip(g_bc, bc_diff) if d]
            return tuple(out)

        # bcast args reaching the engine are the Tensor-valued ones only
        # (forward() filters; layer_call reinserts the None placeholders)
        n_bcast = sum(b is not None for b in bcast_template)
        in_specs = tuple(PartitionSpec(pp) for _ in range(n_keys)) + tuple(
            PartitionSpec() for _ in range(nf + nl + 1 + n_bcast))

        # check_vma/check_rep off: the stage-predicated conds intentionally
        # produce stage-varying values from replicated inputs (the same
        # reason the 2-D-mesh path rides the partial-manual fallback) — the
        # mesh lint, not the rep checker, owns collective congruence here.
        def fwd_sm(*vals):
            return shard_map(
                pipe_fwd, mesh=jmesh, in_specs=in_specs,
                out_specs=(PartitionSpec(), PartitionSpec(pp)),
                axis_names={pp}, check_vma=False)(*vals)

        @jax.custom_vjp
        def zb(*vals):
            return fwd_sm(*vals)[0]

        def zb_fwd(*vals):
            out, store = fwd_sm(*vals)
            return out, (vals, store)

        def zb_bwd(res, g):
            vals, store = res
            x = vals[n_keys + nf + nl]
            bcast_vals = vals[n_keys + nf + nl + 1:]
            x_diff = jnp.issubdtype(x.dtype, jnp.inexact)
            bc_diff = [jnp.issubdtype(b.dtype, jnp.inexact) for b in bcast_vals]
            n_grads = (n_keys + nf + nl + (1 if x_diff else 0)
                       + sum(bc_diff))
            grad_specs = tuple(PartitionSpec(pp) for _ in range(n_keys)) + \
                tuple(PartitionSpec() for _ in range(n_grads - n_keys))
            grads = shard_map(
                pipe_bwd, mesh=jmesh,
                in_specs=in_specs + (PartitionSpec(pp), PartitionSpec()),
                out_specs=grad_specs,
                axis_names={pp}, check_vma=False)(*vals, store, g)
            grads = list(grads)
            out = []
            for i, v in enumerate(vals):
                if i < n_keys + nf + nl:
                    out.append(grads.pop(0))
                elif i == n_keys + nf + nl:  # x
                    out.append(grads.pop(0) if x_diff
                               else np.zeros(v.shape, jax.dtypes.float0))
                else:
                    d = bc_diff[i - (n_keys + nf + nl + 1)]
                    out.append(grads.pop(0) if d
                               else np.zeros(v.shape, jax.dtypes.float0))
            return tuple(out)

        zb.defvjp(zb_fwd, zb_bwd)
        return zb


def pipeline_parallel(model, mesh, schedule: str = None, **kwargs):
    """Model-dispatching pipeline entry (the reference pipeline_parallel.py
    name): convert `model` to run its trunk (and edges, where the model
    pipeliner supports them) over the 'pp' mesh axis under `schedule`
    (None -> FLAGS_pipeline_schedule).  LlamaForCausalLM routes to
    pipeline_llama, GPTForCausalLM to pipeline_gpt; a plain list of
    structurally identical blocks builds a PipelineStack directly."""
    from paddle_tpu.models.gpt import GPTForCausalLM, pipeline_gpt
    from paddle_tpu.models.llama import LlamaForCausalLM, pipeline_llama

    if isinstance(model, LlamaForCausalLM):
        return pipeline_llama(model, mesh, schedule=schedule, **kwargs)
    if isinstance(model, GPTForCausalLM):
        return pipeline_gpt(model, mesh, schedule=schedule, **kwargs)
    if isinstance(model, (list, tuple)):
        return PipelineStack(list(model), mesh, schedule=schedule, **kwargs)
    raise TypeError(
        f"pipeline_parallel: no pipeliner for {type(model).__name__}; use "
        "PipelineStack directly for custom block stacks")
