"""Pipeline schedules as DATA — registry, static simulator, engine lowering.

Reference counterpart: the schedule pass family
(python/paddle/distributed/passes/pipeline_scheduler_pass.py:47-566 —
FThenB / 1F1B variants selected as pass attributes, not hand-written
runtimes) plus the zero-bubble schedule literature (ZB-H1: split the
backward into a grad-INPUT pass B on the critical path and a deferred
grad-WEIGHT pass W that fills the warmup/drain bubbles, keeping 1F1B's
activation memory).

This module owns three faces of "a schedule":

1. **The table** — `Schedule.stage_programs(S, M)` returns, per stage, the
   ordered {F, B, W} slot sequence; `Schedule.table(S, M)` time-aligns it
   into the classic per-tick grid (unit slot costs).  This is the data the
   docs print and the simulator walks.
2. **The simulator** — `simulate(schedule, S, M, costs)` computes makespan,
   bubble fraction and peak activation residency from the table alone:
   CPU-falsifiable proof that ZB-H1's bubble is strictly below 1F1B's at
   equal (S, M) with NO residency growth (the W slots fill waits that
   1F1B's fused backward serializes), no TPU needed.  Slot dependencies:
   F(m,s) needs F(m,s-1); B(m,s) needs B(m,s+1) (or F(m,S-1) on the last
   stage); W(m,s) needs B(m,s).
3. **The engine plan** — `Schedule.engine_plan(S, M)` lowers the table to
   the int32 tick arrays (`b_tick`, `w_tick`) the SPMD split-backward scan
   in pipeline.py consumes.  The SPMD engine runs every stage in ONE
   program, so per-stage idle slots do not exist at runtime; what the plan
   encodes is the *deferral* structure: at backward tick r the scan
   executes the grad-input pass of forward tick `b_tick[r]` and the
   deferred grad-weight pass of forward tick `w_tick[r]` (-1 = none).  A
   future interleaved/VPP-zero-bubble schedule plugs in by registering new
   tables + plan — the scan body never changes.

Selection: `PipelineStack(schedule=None)` (and `pipeline_llama` /
`pipeline_gpt` / the `pipeline_scheduler` pass) resolves the schedule from
`FLAGS_pipeline_schedule`; a flags listener re-resolves flag-following
stacks and drops their cached built steps on change — the same contract
as FLAGS_decode_chunk for serving engines.

The module also owns the pipeline telemetry (`pipeline_stats()`, surfaced
through paddle_tpu.profiler like the serving/checkpoint counters) and the
comm/compute-overlap primitive `overlap_grad_sync` the sharded train step
uses to turn GSPMD's single fused grad all-reduce into a reduce-scatter +
explicit collective-permute all-gather chain XLA's latency-hiding
scheduler can interleave with compute (docs/PIPELINE.md).
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass

from paddle_tpu._core import flags as _flags

__all__ = [
    "Costs", "SimResult", "Schedule", "register_schedule", "get_schedule",
    "available_schedules", "simulate", "pipeline_stats", "overlap_grad_sync",
]


# --------------------------------------------------------------------- costs
@dataclass(frozen=True)
class Costs:
    """Per-slot cost weights.  `f`/`b`/`w` are wall costs of the forward,
    grad-input, and grad-weight passes of ONE stage-microbatch; a FUSED
    backward slot (non-split schedules) costs b + w.  `w_residency` is the
    activation units a split B keeps alive (the stored boundary input +
    output cotangent) until its deferred W runs; a forward slot stores 1
    unit, a fused backward frees it entirely."""

    f: float = 1.0
    b: float = 1.0
    w: float = 1.0
    w_residency: float = 1.0


@dataclass(frozen=True)
class SimResult:
    makespan: float
    bubble_fraction: float      # 1 - useful_work / (S * makespan)
    peak_residency: float       # max over stages of live activation units
    stage_residency: tuple      # per-stage peaks
    total_work: float


# ------------------------------------------------------------------ schedules
class Schedule:
    """Base: a named schedule that can emit per-stage slot programs.

    split_backward=False means the backward is one fused slot (kind "B",
    cost b + w, frees the whole activation); True means B and W are
    separate slots and the engine runs the split-backward scan."""

    name: str = ""
    split_backward: bool = False

    def stage_programs(self, S, M):  # -> list[list[(kind, microbatch)]]
        raise NotImplementedError

    # ---- table: time-aligned per-tick grid (unit slot costs; fused B = 2)
    def table(self, S, M):
        """list of rows, one per tick; row[s] is 'F3'/'B1'/'W0'/'' — the
        classic pipeline diagram, derived from the same simulation the
        bubble numbers come from."""
        costs = Costs(1.0, 1.0, 1.0)
        start, _finish, makespan = _timings(self, S, M, costs)
        n_ticks = int(round(makespan))
        rows = [["" for _ in range(S)] for _ in range(n_ticks)]
        for (kind, m, s), t0 in start.items():
            dur = _slot_cost(kind, costs, self.split_backward)
            for dt in range(int(round(dur))):
                rows[int(round(t0)) + dt][s] = f"{kind}{m}"
        return rows

    # ---- engine lowering (consumed by the split-backward scan)
    def engine_plan(self, S, M):
        """int32 arrays driving the SPMD backward scan: at backward tick r
        run the grad-input pass of forward tick b_tick[r] and the deferred
        grad-weight pass of forward tick w_tick[r] (-1 = no slot).  The
        grad-input chain is ring-ordered (strict reverse forward-tick
        order); the schedule's freedom is the W deferral window."""
        if not self.split_backward:
            raise ValueError(
                f"schedule {self.name!r} has a fused backward; the engine "
                "plan exists only for split-backward schedules")
        T = M + S - 1
        D = self.engine_w_lag(S, M)
        TB = T + D
        b_tick = [T - 1 - r if r < T else -1 for r in range(TB)]
        w_tick = [T - 1 - (r - D) if D <= r < T + D else -1 for r in range(TB)]
        return {"T": T, "D": D, "TB": TB, "b_tick": b_tick, "w_tick": w_tick}

    def engine_w_lag(self, S, M) -> int:
        """Backward-tick deferral of each W slot behind its B slot."""
        raise NotImplementedError

    def bubble_fraction(self, S, M, costs: Costs = Costs()) -> float:
        return simulate(self, S, M, costs).bubble_fraction


class FThenB(Schedule):
    """GPipe: all forwards, then all (fused) backwards.  Fewest recompute
    FLOPs, every stage's activations live through the whole forward."""

    name = "FThenB"

    def stage_programs(self, S, M):
        return [[("F", m) for m in range(M)] + [("B", m) for m in range(M)]
                for _ in range(S)]


class OneFOneB(Schedule):
    """1F1B: warmup of S - s forwards, then strict one-forward-one-backward
    (fused) steady state.  Peak activation residency S - s per stage."""

    name = "1F1B"

    def stage_programs(self, S, M):
        out = []
        for s in range(S):
            warm = min(S - s, M)
            prog = [("F", m) for m in range(warm)]
            nf, nb = warm, 0
            while nb < M:
                prog.append(("B", nb))
                nb += 1
                if nf < M:
                    prog.append(("F", nf))
                    nf += 1
            out.append(prog)
        return out


class ZBH1(Schedule):
    """ZB-H1 zero-bubble: the backward splits into B (grad-input, critical
    path — it feeds the upstream stage) and W (grad-weight, off-path).  A
    stage runs B the moment it is ready, keeps at most the 1F1B warmup
    count of activations in flight, and fills every wait with a pending W
    — the memory-neutral member of the zero-bubble family (peak residency
    equals 1F1B's S - s by construction; the greedy below enforces it as
    a hard cap)."""

    name = "ZB-H1"
    split_backward = True

    def stage_programs(self, S, M):
        # Greedy discrete-event construction with unit costs.  Priority at
        # each stage decision point: B if ready now, else F if ready now
        # and the memory cap (in-flight acts + pending W residuals + 1 <=
        # S - s) allows, else a pending W, else idle to the next dep event.
        costs = Costs(1.0, 1.0, 1.0)
        progs = [[] for _ in range(S)]
        t_free = [0.0] * S
        nf = [0] * S            # next forward microbatch per stage
        nb = [0] * S            # next backward microbatch per stage
        wq = [[] for _ in range(S)]  # pending W microbatches (FIFO)
        finish = {}             # (kind, m, s) -> finish time

        def dep(kind, m, s):
            if kind == "F":
                return finish.get(("F", m, s - 1), 0.0) if s > 0 else 0.0
            if kind == "B":
                key = ("F", m, s) if s == S - 1 else ("B", m, s + 1)
                return finish.get(key)
            return finish.get(("B", m, s))  # W

        def put(kind, m, s, start):
            c = {"F": costs.f, "B": costs.b, "W": costs.w}[kind]
            progs[s].append((kind, m))
            finish[(kind, m, s)] = start + c
            t_free[s] = start + c

        total = 3 * M  # F + B + W slots per stage
        while any(len(progs[s]) < total for s in range(S)):
            progressed = False
            for s in range(S):
                while len(progs[s]) < total:
                    t = t_free[s]
                    cap = S - s
                    live = (nf[s] - nb[s]) + len(wq[s]) * costs.w_residency
                    b_dep = dep("B", nb[s], s) if nb[s] < M else None
                    f_dep = dep("F", nf[s], s) if nf[s] < M else None
                    if b_dep is not None and b_dep <= t:
                        put("B", nb[s], s, t)
                        wq[s].append(nb[s])
                        nb[s] += 1
                    elif (f_dep is not None and f_dep <= t
                          and live + 1 <= cap):
                        put("F", nf[s], s, t)
                        nf[s] += 1
                    elif wq[s]:
                        put("W", wq[s].pop(0), s, t)
                    else:
                        # idle until the earliest known dep event
                        events = [d for d in (b_dep, f_dep)
                                  if d is not None and d > t]
                        if not events:
                            break  # dep not scheduled yet: other stages first
                        t_free[s] = min(events)
                        continue
                    progressed = True
            if not progressed:
                raise RuntimeError(
                    f"ZB-H1 schedule construction deadlocked at S={S}, M={M}")
        return progs

    def engine_w_lag(self, S, M) -> int:
        # The SPMD scan has one uniform timeline; the W deferral window is
        # the worst-case table lag — stage 0 may hold a W through the whole
        # drain, i.e. S - 1 backward ticks (>= 1 so deferred accumulation
        # is structurally exercised even at S == 1... S >= 2 in practice).
        return max(1, S - 1)


# ------------------------------------------------------------------- registry
_REGISTRY: dict = {}


def register_schedule(cls):
    inst = cls()
    if not inst.name:
        raise ValueError("schedule class needs a name")
    _REGISTRY[inst.name] = inst
    return cls


def get_schedule(name: str) -> Schedule:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown pipeline schedule {name!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def available_schedules():
    return sorted(_REGISTRY)


for _cls in (FThenB, OneFOneB, ZBH1):
    register_schedule(_cls)


def resolve_schedule_flag() -> str:
    """FLAGS_pipeline_schedule -> a registered schedule name (loud on a
    typo: a silently ignored schedule flag would fake a perf win)."""
    name = str(_flags.flag("FLAGS_pipeline_schedule"))
    get_schedule(name)
    return name


# ------------------------------------------------------------------ simulator
def _slot_cost(kind, costs: Costs, split: bool) -> float:
    if kind == "F":
        return costs.f
    if kind == "B":
        return costs.b if split else costs.b + costs.w
    return costs.w


def _timings(schedule: Schedule, S, M, costs: Costs):
    """Fixed-point slot timing for the schedule's per-stage programs.
    Start times are uniquely determined by per-stage order + cross-stage
    deps (longest path over a DAG), so iteration order cannot change the
    result."""
    programs = schedule.stage_programs(S, M)
    split = schedule.split_backward
    start, finish = {}, {}
    ptr = [0] * S
    t_free = [0.0] * S

    def dep_time(kind, m, s):
        if kind == "F":
            return finish.get(("F", m, s - 1), 0.0) if s > 0 else 0.0
        if kind == "B":
            key = ("F", m, s) if s == S - 1 else ("B", m, s + 1)
            return finish.get(key)
        return finish.get(("B", m, s))

    remaining = sum(len(p) for p in programs)
    while remaining:
        progressed = False
        for s in range(S):
            while ptr[s] < len(programs[s]):
                kind, m = programs[s][ptr[s]]
                d = dep_time(kind, m, s)
                if d is None:
                    break
                t0 = max(t_free[s], d)
                start[(kind, m, s)] = t0
                finish[(kind, m, s)] = t0 + _slot_cost(kind, costs, split)
                t_free[s] = finish[(kind, m, s)]
                ptr[s] += 1
                remaining -= 1
                progressed = True
        if not progressed:
            stuck = {st: programs[st][ptr[st]] for st in range(S)
                     if ptr[st] < len(programs[st])}
            raise RuntimeError(
                f"schedule {schedule.name!r} has a dependency cycle at "
                f"S={S}, M={M} (stuck slots per stage: {stuck})")
    return start, finish, max(finish.values(), default=0.0)


def simulate(schedule, S, M, costs: Costs = Costs()) -> SimResult:
    """Static evaluation of a schedule's table: makespan, bubble fraction,
    peak per-stage activation residency.  Pure host math — the
    CPU-falsifiable face of every pipeline perf claim (the axon tunnel has
    been down since round 4; see ROADMAP)."""
    if isinstance(schedule, str):
        schedule = get_schedule(schedule)
    programs = schedule.stage_programs(S, M)
    split = schedule.split_backward
    start, _finish, makespan = _timings(schedule, S, M, costs)

    peaks = []
    for s in range(S):
        order = sorted(programs[s], key=lambda km: start[(km[0], km[1], s)])
        live, peak = 0.0, 0.0
        for kind, _m in order:
            if kind == "F":
                live += 1.0
            elif kind == "B":
                live -= 1.0
                if split:
                    live += costs.w_residency
            else:  # W
                live -= costs.w_residency
            peak = max(peak, live)
        peaks.append(peak)

    per_stage_work = M * (costs.f + costs.b + costs.w)
    total = S * per_stage_work
    bubble = 1.0 - total / (S * makespan) if makespan else 0.0
    return SimResult(makespan=makespan, bubble_fraction=bubble,
                     peak_residency=max(peaks), stage_residency=tuple(peaks),
                     total_work=total)


# ------------------------------------------------------------------ telemetry
_STATS = {
    "programs": 0,        # pipeline step programs built/dispatched
    "ticks": 0,           # scan ticks traced (fwd + split-bwd)
    "f_slots": 0,         # stage-microbatch forward slots
    "b_slots": 0,         # grad-input slots (split) or fused backward slots
    "w_slots": 0,         # deferred grad-weight slots (split schedules only)
    "bubble_ticks": 0,    # stage-ticks spent on warmup/drain bubble work
    "overlap_issued": 0,  # collective-permute hops issued by overlap chains
}


def pipeline_stats(reset: bool = False) -> dict:
    """Counters of the pipeline-schedule subsystem (this module owns them —
    one schema, no drift; surfaced via paddle_tpu.profiler.pipeline_stats
    and the Profiler.summary() "Pipeline:" footer).  Counted when a
    pipeline step is BUILT/dispatched from python (once per trace under a
    compiled TrainStep, per call in eager), like the mesh-lint counters."""
    out = dict(_STATS)
    if reset:
        for k in _STATS:
            _STATS[k] = 0
    return out


def _count_program(schedule_name, S, M, n_virtual=1):
    sched = _REGISTRY.get(schedule_name)
    T = M * n_virtual + S - 1
    _STATS["programs"] += 1
    _STATS["f_slots"] += S * M
    _STATS["b_slots"] += S * M
    ticks = T
    if sched is not None and sched.split_backward:
        plan = sched.engine_plan(S, M)
        ticks += plan["TB"]
        _STATS["w_slots"] += S * M
        _STATS["bubble_ticks"] += S * (T - M) + S * (plan["TB"] - M)
    else:
        # fused backward replays the T ticks in reverse (scan transpose)
        ticks += T
        _STATS["bubble_ticks"] += 2 * S * (T - M)
    _STATS["ticks"] += ticks


# ------------------------------------------------- flag-following stacks
_STACKS: "weakref.WeakSet" = weakref.WeakSet()


def register_stack(stack):
    _STACKS.add(stack)


@_flags.on_change
def _on_flag_change(changed):
    # Same contract as FLAGS_decode_chunk for serving engines: any stack
    # that follows the flag re-resolves its schedule and drops every cached
    # built step (the eager dispatch cache is cleared by its own listener).
    if "FLAGS_pipeline_schedule" not in changed:
        return
    try:
        resolve_schedule_flag()
    except ValueError:
        # invalid value: a listener must not blow up set_flags mid-walk —
        # existing stacks keep their schedule; the loud error fires where
        # the flag is actually consumed (new stack construction / resolve)
        return
    for stack in list(_STACKS):
        stack._on_schedule_flag_change()


# --------------------------------------------- comm/compute overlap primitive
def overlap_grad_sync(val, mesh, axis: str):
    """Decompose a GSPMD-fused gradient all-reduce into reduce-scatter +
    an explicit ring all-gather of (axis_size - 1) collective-permute hops.

    `val` is a gradient already summed over `axis` semantically (the loss
    runs over the axis-sharded batch in one program); GSPMD would
    materialize one fused all-reduce right before every use.  Constraining
    the value to be axis-sharded makes XLA emit the reduce-scatter half,
    and the ppermute chain rebuilds the replicated value hop by hop — each
    hop is an independent async collective the latency-hiding scheduler
    can overlap with the optimizer math of already-arrived chunks (and,
    under a ZB pipeline, with the W-pass ticks it does not depend on).
    Values are bit-identical to the fused all-reduce (a gather of shards
    reassociates nothing).

    Returns `val` unchanged when the axis is absent/size-1 or no dim is
    divisible by it.  Statically checkable by the mesh lint: the chain is
    a plain shard_map over `axis` with a full-permutation ppermute.
    """
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec

    from paddle_tpu.distributed.shard_map_compat import shard_map

    jmesh = getattr(mesh, "jax_mesh", mesh)
    if axis not in jmesh.axis_names:
        return val
    n = int(dict(jmesh.shape)[axis])
    if n <= 1 or getattr(val, "ndim", 0) == 0:
        return val
    # shard the largest divisible dim
    dims = sorted(range(val.ndim), key=lambda d: -val.shape[d])
    dim = next((d for d in dims if val.shape[d] % n == 0 and val.shape[d] >= n),
               None)
    if dim is None:
        return val

    spec = [None] * val.ndim
    spec[dim] = axis
    val = lax.with_sharding_constraint(
        val, NamedSharding(jmesh, PartitionSpec(*spec)))

    c = val.shape[dim] // n
    ring = [(r, (r + 1) % n) for r in range(n)]

    def ring_allgather(block):
        import jax.numpy as jnp

        idx = lax.axis_index(axis)
        out_shape = list(block.shape)
        out_shape[dim] = n * c
        out = jnp.zeros(out_shape, block.dtype)

        def place(buf, blk, slot):
            starts = [0] * blk.ndim
            starts[dim] = slot * c
            return lax.dynamic_update_slice(buf, blk, starts)

        out = place(out, block, idx)

        def hop(carry, i):
            blk, buf = carry
            blk = lax.ppermute(blk, axis, ring)
            src = (idx - i - 1) % n
            buf = place(buf, blk, src)
            return (blk, buf), None

        (_, out), _ = lax.scan(hop, (block, out),
                               jnp.arange(n - 1, dtype=jnp.int32))
        return out

    _STATS["overlap_issued"] += n - 1
    in_spec = PartitionSpec(*spec)
    return shard_map(ring_allgather, mesh=jmesh, in_specs=(in_spec,),
                     out_specs=PartitionSpec(), axis_names={axis})(val)
