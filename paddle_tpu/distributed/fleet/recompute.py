"""Activation recompute (reference: python/paddle/distributed/fleet/recompute/
recompute.py:108 RecomputeFunction — PyLayer replay with RNG state restore).

TPU-native: jax.checkpoint (remat) is the principled mechanism — it inserts
optimization barriers so XLA actually rematerializes instead of CSE-ing the
replay, and PRNG keys are part of the traced program so dropout replays
identically without the reference's CUDA seed bookkeeping."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core import autograd as core_ag
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.tensor._ops_common import apply

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True, **kwargs):
    """Run `function(*args)` with activations rematerialized in backward."""
    from paddle_tpu.nn import Layer

    if isinstance(function, Layer):
        state = [t for t in function.state_dict().values()]
    else:
        state = []
    n_state = len(state)

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    other_args = [(i, a) for i, a in enumerate(args) if not isinstance(a, Tensor)]
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

    def pure(*vals):
        state_vals = vals[:n_state]
        arg_vals = vals[n_state:]
        originals = [t._value for t in state]
        try:
            for t, v in zip(state, state_vals):
                t._bind(v)
            full_args = [None] * len(args)
            for (i, a) in other_args:
                full_args[i] = a
            for i, v in zip(tensor_pos, arg_vals):
                full_args[i] = Tensor(v)
            with core_ag.no_grad():
                out = function(*full_args, **kwargs)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t,
                out,
                is_leaf=lambda x: isinstance(x, Tensor),
            )
        finally:
            for t, v in zip(state, originals):
                t._bind(v)

    ckpt_fn = jax.checkpoint(pure)
    return apply("recompute", ckpt_fn, *state, *tensor_args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Segment-wise recompute over a Sequential (reference
    fleet/recompute/recompute_hybrid.py recompute_sequential)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    from paddle_tpu.nn import Sequential

    if isinstance(functions, Sequential):
        layers = list(functions)
    else:
        layers = list(functions)
    n = len(layers)
    seg_size = max(1, n // segments)
    out = args
    i = 0
    while i < n:
        chunk = layers[i : i + seg_size]

        def run_chunk(*xs, _chunk=tuple(chunk)):
            y = xs if len(xs) > 1 else xs[0]
            for l in _chunk:
                y = l(y) if not isinstance(y, tuple) else l(*y)
            return y

        from paddle_tpu.nn import Layer

        class _ChunkLayer(Layer):
            def __init__(self, mods):
                super().__init__()
                for j, m in enumerate(mods):
                    self.add_sublayer(str(j), m)

            def forward(self, *xs):
                y = xs if len(xs) > 1 else xs[0]
                for m in self._sub_layers.values():
                    y = m(y) if not isinstance(y, tuple) else m(*y)
                return y

        wrapper = _ChunkLayer(chunk)
        out = recompute(wrapper, *(out if isinstance(out, tuple) else (out,)))
        out = (out,) if not isinstance(out, tuple) else out
        i += seg_size
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out


def recompute_wrap(layer):
    """Wrap a Layer so its forward runs under activation recompute
    (distributed passes' recompute target helper).  The wrapper IS a Layer
    registering the inner one as a sublayer — parameters stay visible to
    state_dict()/parameters()/optimizers."""
    from paddle_tpu.nn import Layer

    class RecomputeWrapper(Layer):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, *args, **kwargs):
            return recompute(self.inner, *args, **kwargs)

    return RecomputeWrapper(layer)
