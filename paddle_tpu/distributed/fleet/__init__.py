"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py:167)."""

from . import base, layers, meta_parallel, utils  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .fleet import (  # noqa: F401
    DistributedStrategy,
    Fleet,
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
    Role,
    UtilBase,
    util,
    HybridParallelOptimizer,
    PaddleCloudRoleMaker,
    UserDefinedRoleMaker,
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
    init_server,
    init_worker,
    is_initialized,
    is_server,
    is_worker,
    make_train_step,
    run_server,
    stop_worker,
    worker_index,
    worker_num,
)
from .recompute import recompute, recompute_sequential  # noqa: F401
