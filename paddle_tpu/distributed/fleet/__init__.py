"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py:167).

Filled out incrementally: recompute first (used by models), HCG/engines land
with the parallel stack."""

from .recompute import recompute, recompute_sequential  # noqa: F401
