"""Sequence parallelism inside the TP group (Megatron SP).

Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
:85-360 — ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers splitting
activations on the sequence dim across the mp group, Column/Row
SequenceParallelLinear, and register_sequence_parallel_allreduce_hooks.

TPU-native: the scatter/gather pair is a pair of sharding constraints —
GSPMD emits the all-gather before ops needing the full sequence and the
reduce-scatter after row-parallel matmuls (XLA chooses reduce-scatter over
allreduce+split exactly like the hand-written version).  The allreduce hooks
for SP params (layernorms seeing seq-split activations) are unnecessary:
grads are computed on the global program where the sum over sequence shards
is part of the einsum — GSPMD reduces correctly by construction.
"""

from __future__ import annotations

from paddle_tpu._core.autograd import apply
from paddle_tpu._core.tensor import Tensor
import paddle_tpu.nn as nn

from ..layers.mpu.mp_layers import ColumnParallelLinear, RowParallelLinear, _constraint, _mp_mesh

__all__ = [
    "ScatterOp",
    "GatherOp",
    "AllGatherOp",
    "ReduceScatterOp",
    "ColumnSequenceParallelLinear",
    "RowSequenceParallelLinear",
    "mark_as_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
]


def _seq_constraint(x: Tensor, seq_axis: int, shard: bool, mesh=None, mp_axis: str = "mp"):
    mesh, ax = _mp_mesh(mesh, mp_axis)
    if mesh is None:
        return x
    entries = [None] * x.ndim
    if shard:
        entries[seq_axis] = ax
    return _constraint(x, mesh, entries)


class ScatterOp:
    """Split activation along the sequence dim across mp ranks."""

    @staticmethod
    def apply(x, axis=1, mesh=None, mp_axis="mp"):
        return _seq_constraint(x, axis, True, mesh, mp_axis)


class GatherOp:
    """Gather sequence shards back to the full sequence."""

    @staticmethod
    def apply(x, axis=1, mesh=None, mp_axis="mp"):
        return _seq_constraint(x, axis, False, mesh, mp_axis)


class AllGatherOp(GatherOp):
    pass


class ReduceScatterOp:
    """Partial activations reduced and seq-scattered (row-parallel output)."""

    @staticmethod
    def apply(x, axis=1, mesh=None, mp_axis="mp"):
        return _seq_constraint(x, axis, True, mesh, mp_axis)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear whose input arrives sequence-sharded: the
    all-gather(seq) before the matmul is GSPMD-inserted."""

    def __init__(self, *args, seq_axis: int = 1, **kwargs):
        kwargs.setdefault("gather_output", False)
        super().__init__(*args, **kwargs)
        self._seq_axis = seq_axis

    def forward(self, x):
        if self._mesh is not None:
            x = _seq_constraint(x, self._seq_axis, True, self._mesh, self._axis)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output is reduce-scattered on the seq dim."""

    def __init__(self, *args, seq_axis: int = 1, **kwargs):
        kwargs.setdefault("input_is_parallel", True)
        super().__init__(*args, **kwargs)
        self._seq_axis = seq_axis

    def forward(self, x):
        if self._mesh is not None and self.input_is_parallel:
            x = _constraint(x, self._mesh, [None] * (x.ndim - 1) + [self._axis])
        out = self.linear(x)
        if self._mesh is not None:
            out = _seq_constraint(out, self._seq_axis, True, self._mesh, self._axis)
        return out


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True if not hasattr(parameter, "__slots__") else None
    return parameter


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1, fuse_allreduce=False):
    """No-op by design: grads of SP-affected params are already globally
    correct under GSPMD (see module docstring)."""
    return layer
