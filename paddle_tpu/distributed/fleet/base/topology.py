"""Hybrid-parallel process topology.

Reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology (:61) builds an N-d rank grid; HybridCommunicateGroup
(:174) derives per-axis comm groups over the 5 axes
[data, pipe, sharding, sep, model] and fused groups (e.g. check group).

TPU-native: the rank grid IS a jax device mesh.  Groups are mesh-axis Groups
(communication/group.py): collectives over them compile to ICI collectives.
The combinatorial API (get_comm_list, get_rank_from_stage, axis ranks) is
kept — auto-tuner, checkpoint reshard and schedulers use that pure logic.
"""

from __future__ import annotations

import itertools

import numpy as np

from paddle_tpu.distributed.communication.group import Group, new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_HYBRID_ORDER = ["data", "pipe", "sharding", "sep", "model"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or _HYBRID_ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        if len(self._parallel_names) != len(self._dims):
            raise ValueError("names/dims length mismatch")
        self._world = int(np.prod(self._dims))
        self._grid = np.arange(self._world).reshape(self._dims)

    def get_hybrid_group_names(self):
        return list(self._parallel_names)

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **coords):
        if sorted(coords) != sorted(self._parallel_names):
            raise ValueError("must give every axis coordinate")
        idx = tuple(coords[n] for n in self._parallel_names)
        return int(self._grid[idx])

    def get_coord(self, rank):
        pos = np.argwhere(self._grid == rank)
        if len(pos) == 0:
            raise ValueError(f"rank {rank} out of range")
        return tuple(int(i) for i in pos[0])

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        ax = self._parallel_names.index(axis_name)
        taken = np.take(self._grid, index, axis=ax)
        return [int(r) for r in taken.flatten()]

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name`: one group per combination of
        the other axes (reference get_comm_list)."""
        ax = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._grid, ax, -1).reshape(-1, self._dims[ax])
        return [[int(r) for r in row] for row in moved]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = dict(zip(self._parallel_names, self.get_coord(global_rank)))
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """Per-axis communication groups over the hybrid topology.

    Reference topology.py:174 — builds NCCL groups per axis; here each axis
    is a mesh axis and the Group is a handle onto it.  The 5-axis order
    [data, pipe, sharding, sep, model] matches the reference (sep added
    between sharding and model, topology.py:184-246).
    """

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self._global_rank = global_rank
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("model")

        coord = topology.get_coord(global_rank)
        self._coord = dict(zip(topology.get_hybrid_group_names(), coord))

        self._dp_group = self._make_group("data")
        self._pp_group = self._make_group("pipe")
        self._sharding_group = self._make_group("sharding")
        self._sep_group = self._make_group("sep") if self._sep_degree > 1 else None
        self._mp_group = self._make_group("model")

    # groups are tagged with the MESH axis name (the one as_process_mesh
    # emits and the engines put in collective_axis_scope), so collectives
    # over HCG groups resolve inside the SPMD step
    _MESH_AXIS = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}

    def _make_group(self, axis_name) -> Group:
        ranks = None
        for grp in self._topo.get_comm_list(axis_name):
            if self._global_rank in grp:
                ranks = grp
                break
        g = new_group(ranks=ranks)
        g.axis = self._MESH_AXIS.get(axis_name, axis_name)
        return g

    # ------------------------------------------------------------- topology
    @property
    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and self._dp_degree > 1:
            return "data"
        if self._pp_degree > 1:
            return "pipe"
        if self._mp_degree > 1:
            return "model"
        if self._sharding_degree > 1:
            return "sharding"
        return "single"

    def get_global_rank(self):
        return self._global_rank

    # --------------------------------------------------------------- per-axis
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return self._coord["pipe"]

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_rank(self):
        return self._coord.get("sep", 0)

    def get_sep_parallel_group(self):
        return self._sep_group

    # ---------------------------------------------------------------- pipes
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None  # SPMD pipeline uses ppermute, not explicit p2p rings

    # ------------------------------------------------------------------ mesh
    def as_process_mesh(self, skip_trivial=True):
        """The HCG grid as a ProcessMesh ('data'→'dp', 'model'→'mp', …) for
        the GSPMD engines."""
        from paddle_tpu.distributed.auto_parallel import ProcessMesh

        rename = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}
        names = self._topo.get_hybrid_group_names()
        dims = [self._topo.get_dim(n) for n in names]
        keep = [(rename.get(n, n), d) for n, d in zip(names, dims) if d > 1 or not skip_trivial]
        if not keep:
            keep = [("dp", 1)]
        shape = [d for _, d in keep]
        axis_names = [n for n, _ in keep]
        ids = np.arange(int(np.prod(shape))).reshape(shape)
        return ProcessMesh(ids, axis_names)
