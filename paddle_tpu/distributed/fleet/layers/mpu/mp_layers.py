"""Megatron-style tensor-parallel layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding (:47), ColumnParallelLinear (:333),
RowParallelLinear (:540), ParallelCrossEntropy — plus mp_ops.py identity/
allreduce/split/gather PyLayers.

TPU-native: the layer keeps GLOBAL weight shapes; parallelism is a
NamedSharding placement on the weight plus sharding constraints on
activations.  GSPMD then inserts exactly the collectives mp_ops.py writes by
hand (identity fwd + allreduce bwd for column, allreduce fwd for row, …) —
on ICI, fused into the step program.  The construction-time arguments
(gather_output, input_is_parallel, has_bias) keep reference semantics by
placing or omitting output constraints.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu._core.autograd import apply
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelCrossEntropy",
]


def _mp_mesh(mesh=None, axis="mp"):
    from paddle_tpu.distributed.auto_parallel import get_mesh

    m = mesh if mesh is not None else get_mesh()
    if m is None or axis not in m.dim_names:
        return None, axis
    return m, axis


def _constraint(x: Tensor, mesh, spec_entries) -> Tensor:
    """Differentiable sharding constraint on an activation."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(mesh.jax_mesh, PartitionSpec(*spec_entries))
    return apply("sharding_constraint", lambda v: jax.lax.with_sharding_constraint(v, sh), x)


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None, mesh=None, mp_axis="mp"):
        super().__init__()
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard, shard_tensor

        self.embedding = nn.Embedding(num_embeddings, embedding_dim, weight_attr=weight_attr)
        self._mesh, self._axis = _mp_mesh(mesh, mp_axis)
        if self._mesh is not None:
            idx = self._mesh.dim_names.index(self._axis)
            pl = [Replicate()] * self._mesh.ndim
            pl[idx] = Shard(0)  # vocab dim
            shard_tensor(self.embedding.weight, self._mesh, pl)

    @property
    def weight(self):
        return self.embedding.weight

    def forward(self, x):
        return self.embedding(x)


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None,
                 mesh=None, mp_axis="mp"):
        super().__init__()
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard, shard_tensor

        self.linear = nn.Linear(in_features, out_features, weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.gather_output = gather_output
        self._mesh, self._axis = _mp_mesh(mesh, mp_axis)
        if self._mesh is not None:
            idx = self._mesh.dim_names.index(self._axis)
            pl = [Replicate()] * self._mesh.ndim
            pl[idx] = Shard(1)  # output-features dim of [in, out] weight
            shard_tensor(self.linear.weight, self._mesh, pl)
            if has_bias:
                plb = [Replicate()] * self._mesh.ndim
                plb[idx] = Shard(0)
                shard_tensor(self.linear.bias, self._mesh, plb)

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        out = self.linear(x)
        if self._mesh is not None:
            nd = out.ndim
            if self.gather_output:
                out = _constraint(out, self._mesh, [None] * nd)
            else:
                out = _constraint(out, self._mesh, [None] * (nd - 1) + [self._axis])
        return out


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None,
                 mesh=None, mp_axis="mp"):
        super().__init__()
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard, shard_tensor

        self.linear = nn.Linear(in_features, out_features, weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.input_is_parallel = input_is_parallel
        self._mesh, self._axis = _mp_mesh(mesh, mp_axis)
        if self._mesh is not None:
            idx = self._mesh.dim_names.index(self._axis)
            pl = [Replicate()] * self._mesh.ndim
            pl[idx] = Shard(0)  # input-features dim
            shard_tensor(self.linear.weight, self._mesh, pl)
            # bias replicated (applied after the implicit allreduce)

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        if self._mesh is not None and self.input_is_parallel:
            nd = x.ndim
            x = _constraint(x, self._mesh, [None] * (nd - 1) + [self._axis])
        out = self.linear(x)
        if self._mesh is not None:
            out = _constraint(out, self._mesh, [None] * out.ndim)  # replicated (allreduce)
        return out


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax cross entropy (reference mp_layers.py
    ParallelCrossEntropy over c_softmax_with_cross_entropy).  GSPMD computes
    the partial-max/partial-sum collectives from the logits' sharding."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)
