"""Elastic training manager (reference:
python/paddle/distributed/fleet/elastic/manager.py:126 ElasticManager —
etcd-registered membership, fault detect, scale up/down, relaunch).

TPU-native redesign: membership lives in the framework's native TCPStore
(the launcher's rendezvous store) instead of etcd — each node heartbeats a
key; the manager watches peer heartbeats and reports JOIN/GONE transitions
so the launcher can relaunch with a new world spec.  np can be a range
("2:4") exactly like the reference."""

from __future__ import annotations

import threading
import time

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


def _parse_np(np_spec):
    """'4' → (4, 4); '2:4' → (2, 4) (reference manager.py np range parse)."""
    if isinstance(np_spec, int):
        return np_spec, np_spec
    parts = str(np_spec).split(":")
    if len(parts) == 1:
        n = int(parts[0])
        return n, n
    return int(parts[0]), int(parts[1])


class ElasticManager:
    """reference manager.py:126 — here backed by TCPStore heartbeats."""

    def __init__(self, endpoint, node_id, np_spec, heartbeat_interval=2.0,
                 timeout=10.0, is_host=False):
        from paddle_tpu.distributed.bootstrap import host_or_connect

        self.node_id = str(node_id)
        self.min_np, self.max_np = _parse_np(np_spec)
        self.interval = heartbeat_interval
        self.timeout = timeout
        self._server, self._cli = host_or_connect(endpoint, is_host, timeout_ms=60_000)
        self._stop = threading.Event()
        self._thread = None
        self._known = set()
        self._transitions = []
        self._lock = threading.Lock()

    # membership ----------------------------------------------------------
    def register(self):
        from paddle_tpu.distributed.bootstrap import register_member

        self._cli.set(f"elastic/alive/{self.node_id}", str(time.time()).encode())
        # per-index keys via an atomic counter: a read-modify-write of one
        # list key would lose concurrent registrations
        register_member(self._cli, "elastic/registry", self.node_id)

    def _members(self):
        from paddle_tpu.distributed.bootstrap import list_members

        try:
            return set(list_members(self._cli, "elastic/registry"))
        except Exception:
            return set()

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            self._cli.set(f"elastic/alive/{self.node_id}", str(time.time()).encode())
            now = time.time()
            current = set()
            for m in self._members():
                try:
                    ts = float(self._cli.get(f"elastic/alive/{m}", timeout_ms=1000).decode())
                    if now - ts < self.timeout:
                        current.add(m)
                except Exception:
                    pass
            with self._lock:
                joined = current - self._known
                gone = self._known - current
                for m in joined:
                    self._transitions.append(("JOIN", m))
                for m in gone:
                    self._transitions.append(("GONE", m))
                self._known = current
            self._stop.wait(self.interval)

    def start(self):
        self.register()
        with self._lock:
            self._known = {self.node_id}
        self._thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._thread.start()

    def pop_transitions(self):
        with self._lock:
            out, self._transitions = self._transitions, []
            return out

    def peek_transitions(self):
        with self._lock:
            return list(self._transitions)

    def world(self):
        with self._lock:
            return sorted(self._known)

    # policy --------------------------------------------------------------
    def exit_status(self):
        """RESTART if membership changed but still viable; HOLD if below
        min_np; COMPLETED if unchanged (reference manager exit logic)."""
        n = len(self.world())
        if n < self.min_np:
            return ElasticStatus.HOLD
        # peek — the launcher owns consumption via pop_transitions()
        if self.peek_transitions():
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self._cli.close()
        if self._server:
            self._server.stop()
