"""DataParallel wrapper.

Reference: python/paddle/distributed/parallel.py (~:371) — wraps a Layer,
registers EagerReducer bucketed allreduce hooks (reducer.cc) over the DP
process group.

TPU-native: data parallelism is a batch sharding.  When the train step runs
with the batch sharded over 'dp' (ShardedTrainStep / fleet.make_train_step),
gradient averaging is compiled into the step (psum on ICI) — no reducer, no
buckets, no hooks.  This wrapper exists for API parity: it forwards to the
inner layer and keeps the reference's helper surface (scale_loss,
no_sync, state_dict passthrough).
"""

from __future__ import annotations

import contextlib

from paddle_tpu.nn import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1,
                 find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Identity: the compiled step's pmean already averages over dp."""
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        """Grad-accumulation guard (reference suspends allreduce).  Compiled
        SPMD steps sync only at optimizer.step, so nothing to suspend."""
        yield

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
