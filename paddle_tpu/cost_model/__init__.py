"""Cost model: profiled op table + analytical estimates.

Reference: python/paddle/cost_model/ (CostModel.profile_measure over a
static Program + static_op_benchmark.json, the profiled per-op latency
table consumed by auto-parallel planners) and
paddle/fluid/framework/ir/cost_model.cc.

TPU-native: two tiers —
- `OpCostModel.measure(fn, *args)` profiles a jitted callable on the LIVE
  device (compile once, time steady-state) and records it in the table;
  tables round-trip to JSON like static_op_benchmark.json.
- `flops_time(flops, bytes)` gives the roofline estimate from the device's
  peak FLOPs/HBM bandwidth — the planner's fallback when no profile exists
  (auto_tuner's memory model is the capacity side of the same planning).
"""

from __future__ import annotations

import json
import time

__all__ = ["OpCostModel", "device_peaks"]

# (peak TFLOP/s bf16, HBM GB/s) per device kind — public spec sheet numbers
_PEAKS = {
    "tpu v5 lite": (197.0, 819.0),
    "tpu v5e": (197.0, 819.0),
    "tpu v5p": (459.0, 2765.0),
    "tpu v4": (275.0, 1228.0),
    "cpu": (0.5, 50.0),
}


def device_peaks():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for k, v in _PEAKS.items():
        if k in kind:
            return v
    # unknown accelerator: conservative placeholder so roofline estimates
    # stay finite (profiled measurements are the authoritative path)
    return (100.0, 500.0)


class OpCostModel:
    """Profiled per-op latency table (static_op_benchmark.json analog).

    Table entries are keyed by (name, shape-key) — like
    ops.autotune.AutotuneCache._key_str — so two shapes of the same op
    never overwrite each other; `save()`/`load()` round-trip the full
    per-shape table.  `query(name)` resolves a bare name when it was
    measured at exactly one shape signature; a name measured at several
    shapes must be queried by its full table key (`table_key`)."""

    def __init__(self):
        self.table: dict[str, dict] = {}

    @staticmethod
    def shape_key(args) -> str:
        """Signature of example args: 'a0=16x32:float32|a1=...'."""
        parts = []
        for i, a in enumerate(args):
            shape = tuple(getattr(a, "shape", ()) or ())
            dt = getattr(a, "dtype", None)
            dt = str(dt) if dt is not None else type(a).__name__
            parts.append(f"a{i}={'x'.join(map(str, shape)) or 'scalar'}:{dt}")
        return "|".join(parts)

    def table_key(self, name, args) -> str:
        sk = self.shape_key(args)
        return f"{name}|{sk}" if sk else name

    def measure(self, name, fn, *args, iters=10, warmup=2):
        """Profile a jax-jittable callable; records and returns seconds/call."""
        import jax

        from paddle_tpu.device import hard_sync

        jfn = jax.jit(fn)
        out = jfn(*args)
        hard_sync(out)  # true barrier — block_until_ready lies on the
        for _ in range(warmup):  # remote transport (see device.hard_sync)
            out = jfn(*args)
        hard_sync(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*args)
        hard_sync(out)
        dt = (time.perf_counter() - t0) / iters
        self.table[self.table_key(name, args)] = {
            "time_s": dt,
            "device": str(jax.devices()[0].device_kind),
            "op": name,
        }
        return dt

    def query(self, name, default=None):
        exact = self.table.get(name)
        prefix = name + "|"
        matches = [k for k, v in self.table.items()
                   if k != name and (k.startswith(prefix)
                                     or v.get("op") == name)]
        if exact is not None and not matches:
            return exact["time_s"]  # full table key, or sole bare entry
        if exact is None and len(matches) == 1:
            return self.table[matches[0]]["time_s"]
        if exact is not None or matches:
            # several shape signatures — or a bare legacy entry (e.g. from
            # from_bench_ops) ALONGSIDE fresh per-shape measurements: never
            # silently pick one (the stale bare entry used to shadow the
            # fresh measurement)
            if default is not None:
                return default
            example = matches[0] if matches else name
            raise KeyError(
                f"op {name!r} recorded at {len(matches) + (exact is not None)} "
                f"shape signatures/entries; query the full table key (e.g. "
                f"{example!r})")
        if default is not None:
            return default
        raise KeyError(f"no profile for op {name!r}")

    def flops_time(self, flops, mem_bytes=0):
        """Roofline estimate: max(compute-bound, bandwidth-bound) seconds."""
        peak_tflops, hbm_gbs = device_peaks()
        t_compute = flops / (peak_tflops * 1e12)
        t_mem = mem_bytes / (hbm_gbs * 1e9)
        return max(t_compute, t_mem)

    def estimate_step(self, fn, *example_args):
        """Roofline estimate for a whole jitted step WITHOUT running it:
        flops/bytes come from XLA's cost analysis of the compiled
        executable (profiler.cost_analysis), fed through the device
        roofline — the per-config cost the auto-parallel planner ranks
        with when no measurement exists."""
        from paddle_tpu.profiler import cost_analysis

        analyses = cost_analysis(fn, *example_args)
        flops = float(analyses.get("flops", 0.0) or 0.0)
        mem = float(analyses.get("bytes accessed", 0.0) or 0.0)
        return self.flops_time(flops, mem)

    # ---------------------------------------------------------------- io
    def save(self, path):
        with open(path, "w") as f:
            json.dump(self.table, f, indent=1)

    @classmethod
    def load(cls, path):
        m = cls()
        with open(path) as f:
            m.table = json.load(f)
        return m

    @classmethod
    def from_bench_ops(cls, path_or_dict):
        """Build a table from tools/bench_ops.py results (the shipped
        profiled-table role of the reference's
        python/paddle/cost_model/static_op_benchmark.json: the on-chip
        queue captures bench_ops_results.json per device kind)."""
        m = cls()
        if isinstance(path_or_dict, (str, bytes)):
            with open(path_or_dict) as f:
                data = json.load(f)
        else:
            data = dict(path_or_dict)
        kind = data.get("device_kind", "unknown")
        for name, entry in (data.get("ops") or {}).items():
            if "ms" in entry:
                m.table[name] = {"time_s": float(entry["ms"]) / 1e3,
                                 "device": kind}
        return m
