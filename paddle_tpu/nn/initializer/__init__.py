"""Initializers (reference: python/paddle/nn/initializer/).

Each initializer produces a concrete jax value via `_init_value(shape, dtype)`
— there is no deferred "init op" as in the reference's static graph; XLA
constant-folds initialization into the first step when jitted.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core import random as rng

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Dirac",
    "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity {nonlinearity}")
    return gains[nonlinearity]


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weights are [in, out] in paddle convention.
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def _init_value(self, shape, dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        param._bind(self._init_value(tuple(param.shape), param._value.dtype))
        return param


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def _init_value(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def _init_value(self, shape, dtype):
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * self.std + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _init_value(self, shape, dtype):
        z = jax.random.truncated_normal(rng.next_key(), self.a, self.b, shape, jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def _init_value(self, shape, dtype):
        return jax.random.uniform(rng.next_key(), shape, jnp.float32, self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init_value(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init_value(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng.next_key(), shape, jnp.float32, -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _init_value(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _init_value(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rng.next_key(), shape, jnp.float32, -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _init_value(self, shape, dtype):
        from paddle_tpu._core.tensor import Tensor

        v = self.value._value if isinstance(self.value, Tensor) else jnp.asarray(np.asarray(self.value))
        return v.reshape(shape).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0, name=None):
        self.gain = gain

    def _init_value(self, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal initializer needs >=2 dims")
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(rng.next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups: int = 1, name=None):
        self.groups = groups

    def _init_value(self, shape, dtype):
        # conv weight [out, in, *kernel]
        out_c, in_c = shape[0], shape[1]
        kernel = shape[2:]
        v = np.zeros(shape, np.float32)
        centers = tuple(k // 2 for k in kernel)
        min_c = min(out_c // self.groups, in_c)
        for g in range(self.groups):
            for i in range(min_c):
                idx = (g * (out_c // self.groups) + i, i) + centers
                v[idx] = 1.0
        return jnp.asarray(v, dtype)


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for transposed conv (reference:
    python/paddle/nn/initializer/Bilinear): weight [out, in, kh, kw] filled
    with the bilinear interpolation kernel of its spatial size."""

    def _init_value(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D conv weight")
        out_c, in_c, kh, kw = shape
        def kern(k):
            f = (k + 1) // 2
            c = f - 1 if k % 2 == 1 else f - 0.5
            return 1.0 - np.abs(np.arange(k) - c) / f
        w2d = np.outer(kern(kh), kern(kw)).astype(np.float32)
        v = np.zeros(shape, np.float32)
        for o in range(out_c):
            for i in range(in_c):
                v[o, i] = w2d
        return jnp.asarray(v, dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Override the default param initializers used when a layer's ParamAttr
    has none (reference: python/paddle/nn/initializer/set_global_initializer).
    Pass None to reset."""
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


def _default_init(is_bias):
    if is_bias:
        return _global_bias_init
    return _global_weight_init

__all__ += ["Bilinear", "set_global_initializer", "calculate_gain"]
