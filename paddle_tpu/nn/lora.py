"""LoRA: low-rank adapters for fine-tuning and multi-tenant serving.

Reference lineage: PaddleNLP's LoRA/PEFT tier over ``paddle.nn`` — the
headline parameter-efficient scenario beyond a single base model is ONE
base model serving thousands of tenants, each with its own low-rank
adapter (Hu et al., "LoRA: Low-Rank Adaptation of Large Language Models").

Two faces, one math (``h += (x @ A) @ B * alpha/rank``):

- **Training** (:class:`LoRALinear`, :func:`apply_lora`): surgery replaces
  target ``nn.Linear`` layers in place, keeping their state-dict keys
  (``q_proj.weight`` stays ``q_proj.weight``; the adapter adds
  ``q_proj.lora_A`` / ``q_proj.lora_B``), freezes everything but the
  adapters, and fine-tunes through the ordinary TrainStep.  ``merge()`` /
  ``unmerge()`` fold the adapter into the base weight for adapter-free
  inference; :func:`lora_state_dict` extracts the adapter-only checkpoint
  that CheckpointManager saves/restores (restore prunes the request to
  saved keys, so an adapter-only checkpoint loads into a full model).

- **Serving** (:class:`AdapterPack`): up to ``FLAGS_lora_max_adapters``
  adapters' A/B matrices stacked on a leading SLOT axis, per decoder layer
  — exactly the ``nn.LayerStack`` stacked-leading-axis trick applied to
  adapters.  The pack threads through ``LayerStack.decode_scan`` as
  per-layer xs, the jitted decode step gathers each batch row's A/B by a
  slot-index vector, and a macro-step full of DIFFERENT tenants decodes in
  ONE compiled dispatch.  Slot 0 is reserved as the zero adapter (A = B =
  scaling = 0): base-model requests ride the same program as an exact
  identity.  Hot-swapping mutates pack *contents* (device scatter into a
  pre-allocated slot); the pack *geometry* (slot count, rank, targets)
  never changes, so compiled decode steps are reused across swaps — zero
  recompiles (serving.GenerationEngine, docs/LORA.md).
"""

from __future__ import annotations

import contextlib
import itertools
import re

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu._core import flags as _flags
from paddle_tpu._core.tensor import Parameter, Tensor
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.common import Linear
from paddle_tpu.nn.layer.stack import LayerStack

__all__ = [
    "LoRALinear",
    "AdapterPack",
    "apply_lora",
    "lora_state_dict",
    "parse_adapter_state_dict",
    "adapter_prefill_scope",
    "lora_delta",
    "LLAMA_TARGETS",
]

# Leaf layer names apply_lora targets by default: the attention q/k/v/out
# and MLP projections of models/llama.py and models/gpt.py.
DEFAULT_TARGET_NAMES = (
    "q_proj", "k_proj", "v_proj", "o_proj", "out_proj",
    "gate_up_proj", "down_proj", "fc_in", "fc_out",
)

# Per-decoder-layer projection paths the serving AdapterPack covers (the
# engine's decode step knows exactly these injection points —
# models/llama._decode_layer_paged).
LLAMA_TARGETS = (
    "self_attn.q_proj", "self_attn.k_proj", "self_attn.v_proj",
    "self_attn.o_proj", "mlp.gate_up_proj", "mlp.down_proj",
)


class LoRALinear(Linear):
    """``nn.Linear`` plus a rank-``r`` adapter: ``y = xW + b + (x A) B s``
    with ``s = alpha / rank``.

    Subclasses Linear ON PURPOSE: the base weight keeps its registry name
    (``weight``/``bias``), so swapping a Linear for a LoRALinear changes
    NO existing state-dict keys — base checkpoints keep loading, TP
    placement walks keep finding ``weight`` — and only adds
    ``lora_A``/``lora_B``.  ``lora_B`` initializes to zero (the adapted
    model starts exactly at the base model); ``lora_A`` draws a small
    normal so gradients flow from step one.
    """

    def __init__(self, in_features, out_features, rank, alpha=None,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         bias_attr=bias_attr, name=name)
        rank = int(rank)
        if rank < 1:
            raise ValueError(f"LoRA rank must be >= 1, got {rank}")
        self.rank = rank
        self.alpha = float(alpha) if alpha is not None else float(rank)
        self.scaling = self.alpha / self.rank
        self.merged = False
        self.lora_A = self.create_parameter(
            [in_features, rank], default_initializer=I.Normal(0.0, 0.02))
        self.lora_B = self.create_parameter(
            [rank, out_features], default_initializer=I.Constant(0.0))

    @classmethod
    def from_linear(cls, linear: Linear, rank, alpha=None) -> "LoRALinear":
        """Wrap an existing Linear: the base ``weight``/``bias`` Parameter
        OBJECTS are adopted (no copy — optimizer identity and shardings
        survive) and the adapter params are created in the weight's
        dtype."""
        m = cls(linear.in_features, linear.out_features, rank, alpha=alpha,
                bias_attr=False if linear.bias is None else None)
        m._parameters["weight"] = linear.weight
        if linear.bias is not None:
            m._parameters["bias"] = linear.bias
        dt = linear.weight._value.dtype
        for key in ("lora_A", "lora_B"):
            p = m._parameters[key]
            p._bind(p._value.astype(dt))
        m.training = linear.training
        return m

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.merged:
            return out
        return out + F.linear(F.linear(x, self.lora_A), self.lora_B) * self.scaling

    def _delta_weight(self):
        return (self.lora_A._value @ self.lora_B._value) * jnp.asarray(
            self.scaling, self.lora_A._value.dtype)

    def merge(self):
        """Fold ``A @ B * s`` into the base weight (adapter-free serving of
        the adapted function).  Idempotent."""
        if not self.merged:
            self.weight._bind(
                self.weight._value
                + self._delta_weight().astype(self.weight._value.dtype))
            self.merged = True
        return self

    def unmerge(self):
        """Inverse of :meth:`merge`."""
        if self.merged:
            self.weight._bind(
                self.weight._value
                - self._delta_weight().astype(self.weight._value.dtype))
            self.merged = False
        return self

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, rank={self.rank}, "
                f"alpha={self.alpha}")


def apply_lora(model, rank, alpha=None, targets=None, freeze_base=True):
    """Replace every target ``nn.Linear`` in ``model`` with a
    :class:`LoRALinear` (in place) and freeze the base parameters.

    ``targets``: leaf layer names to adapt (default: the llama/gpt
    attention q/k/v/out + MLP projections).  ``freeze_base=True`` sets
    ``stop_gradient`` on every pre-existing parameter so a TrainStep over
    the model fine-tunes ONLY the adapters (frozen-base contract).
    Returns the model.

    Raises on ``nn.LayerStack`` decoder stacks: the stack's parameters are
    already stacked/fused, so per-layer surgery cannot reach them — build
    the fine-tuning model with ``fuse_layer_stack=False`` (serving a
    LayerStack engine with adapters goes through :class:`AdapterPack`
    instead, which IS the stacked form).
    """
    targets = tuple(targets) if targets is not None else DEFAULT_TARGET_NAMES
    for path, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, LayerStack):
            raise ValueError(
                f"apply_lora: {path or 'model'!r} is an nn.LayerStack "
                "(fuse_layer_stack/FLAGS_scan_layers); per-layer adapter "
                "surgery needs unstacked layers — build the fine-tune "
                "model with fuse_layer_stack=False (serving uses "
                "AdapterPack, the stacked form)")
    if freeze_base:
        for p in model.parameters():
            p.stop_gradient = True
    replaced = 0
    for _path, sub in model.named_sublayers(include_self=True):
        for name, child in list(sub._sub_layers.items()):
            if (name in targets and isinstance(child, Linear)
                    and not isinstance(child, LoRALinear)):
                sub._sub_layers[name] = LoRALinear.from_linear(
                    child, rank, alpha=alpha)
                replaced += 1
    if not replaced:
        raise ValueError(
            f"apply_lora: no Linear layer named any of {targets} found")
    return model


def lora_state_dict(model) -> dict:
    """The adapter-only state dict: every ``*.lora_A`` / ``*.lora_B``
    entry of ``model.state_dict()`` — the checkpoint a fine-tune saves
    (CheckpointManager accepts a plain dict) and a fresh serving engine
    registers via ``GenerationEngine.register_adapter``."""
    out = {k: v for k, v in model.state_dict().items()
           if k.rsplit(".", 1)[-1] in ("lora_A", "lora_B")}
    if not out:
        raise ValueError("lora_state_dict: model has no LoRA parameters "
                         "(run apply_lora first)")
    return out


_LAYER_KEY = re.compile(r"(?:^|\.)layers\.(\d+)\.(.+)\.lora_([AB])$")


def parse_adapter_state_dict(state_dict, num_layers, targets, rank):
    """Adapter checkpoint -> per-target stacked arrays for an AdapterPack.

    Keys like ``model.layers.{i}.self_attn.q_proj.lora_A`` group into
    ``{target: (A [L, in, r], B [L, r, out])}``.  Targets absent from the
    checkpoint (an adapter trained on a subset of projections) come back
    as zeros; keys naming a projection OUTSIDE ``targets`` are loud — the
    pack has no injection point for them.
    """
    per = {}
    for key, val in state_dict.items():
        m = _LAYER_KEY.search(key)
        if m is None:
            if key.rsplit(".", 1)[-1] in ("lora_A", "lora_B"):
                raise ValueError(
                    f"adapter key {key!r} does not name a decoder layer "
                    "(expected ...layers.<i>.<proj>.lora_A/B)")
            continue
        li, target, which = int(m.group(1)), m.group(2), m.group(3)
        if target not in targets:
            raise ValueError(
                f"adapter key {key!r} targets {target!r}, which this "
                f"pack's geometry does not cover (targets={targets})")
        if li >= num_layers:
            raise ValueError(
                f"adapter key {key!r}: layer {li} >= num_layers {num_layers}")
        # normalize through HOST numpy: source tensors arrive with varying
        # jax placement/commitment (freshly trained = uncommitted device,
        # checkpoint-restored = committed unpinned_host, ...) and a
        # committed operand is a DIFFERENT executable signature — the
        # install scatter would recompile per source kind where a warm
        # hot-swap must not.  Registration is a rare control-plane op;
        # one host round-trip here buys one stable signature forever.
        arr = np.asarray(val._value if isinstance(val, Tensor) else val)
        r = arr.shape[-1] if which == "A" else arr.shape[0]
        if r != rank:
            raise ValueError(
                f"adapter rank {r} (key {key!r}) != pack rank {rank} — "
                "pack geometry is fixed at engine construction")
        per.setdefault(target, {})[(li, which)] = arr
    out = {}
    for target, entries in per.items():
        # A and B must pair up PER LAYER: a layer holding only one half
        # (truncated/corrupt checkpoint) would otherwise zero-fill the
        # other and silently serve a crippled delta
        layers_a = {i for (i, w) in entries if w == "A"}
        layers_b = {i for (i, w) in entries if w == "B"}
        if layers_a != layers_b:
            odd = sorted(layers_a ^ layers_b)
            raise ValueError(
                f"adapter state dict for {target!r} is lopsided: layers "
                f"{odd} hold only one of lora_A/lora_B — every layer "
                "must carry both (or neither)")
        a0 = next(v for (_, w), v in entries.items() if w == "A")
        b0 = next(v for (_, w), v in entries.items() if w == "B")
        # stacked in numpy, converted once: uncommitted default-placement
        # arrays, identical signature for every adapter source
        A = jnp.asarray(np.stack([entries.get((i, "A"), np.zeros_like(a0))
                                  for i in range(num_layers)]))
        B = jnp.asarray(np.stack([entries.get((i, "B"), np.zeros_like(b0))
                                  for i in range(num_layers)]))
        out[target] = (A, B)
    if not out:
        raise ValueError("adapter state dict holds no lora_A/lora_B keys")
    return out


def _resolve_sublayer(layer, path):
    out = layer
    for part in path.split("."):
        out = out._sub_layers[part]
    return out


class AdapterPack:
    """Stacked multi-tenant adapter storage for the serving decode step.

    Per target projection ``t``: ``A[t]`` of shape ``[L, S, in, r]`` and
    ``B[t]`` of shape ``[L, S, r, out]`` (L decoder layers, S slots), plus
    ``scaling`` ``[S]`` float32 (``alpha/rank`` per slot).  Slot 0 is the
    reserved zero adapter — base-model identity.  ``S - 1`` usable slots
    come from ``max_adapters`` (default ``FLAGS_lora_max_adapters``).

    The GEOMETRY (L, S, rank, targets, dtype) is frozen at construction;
    :meth:`set_slot` / :meth:`clear_slot` mutate CONTENTS only (device
    scatter at a slot index), so every array keeps its shape and a jitted
    step taking the pack as arguments never recompiles on a swap.
    """

    def __init__(self, model, rank, alpha=None, max_adapters=None,
                 targets=None):
        layers = model.model.layers
        self.num_layers = len(layers)
        self.rank = int(rank)
        if self.rank < 1:
            raise ValueError(f"AdapterPack rank must be >= 1, got {rank}")
        self.alpha = float(alpha) if alpha is not None else float(rank)
        n_ad = (int(max_adapters) if max_adapters is not None
                else int(_flags.flag("FLAGS_lora_max_adapters")))
        if n_ad < 1:
            raise ValueError(
                f"max_adapters (FLAGS_lora_max_adapters) must be >= 1, "
                f"got {n_ad}")
        self.num_slots = n_ad + 1  # slot 0 = reserved zero adapter
        self.targets = tuple(targets) if targets is not None else LLAMA_TARGETS
        blk = layers[0]
        self.ab = {}
        # one zero slot template per target, built NOW: set_slot (omitted
        # targets) and clear_slot scatter these instead of minting fresh
        # jnp.zeros at swap time — hot-swap stays compile-free
        self._zero_slot = {}
        L, S, r = self.num_layers, self.num_slots, self.rank
        for t in self.targets:
            lin = _resolve_sublayer(blk, t)
            if not isinstance(lin, Linear):
                raise TypeError(
                    f"AdapterPack target {t!r} is {type(lin).__name__}, "
                    "expected nn.Linear")
            dt = lin.weight._value.dtype
            self.ab[t] = (jnp.zeros((L, S, lin.in_features, r), dt),
                          jnp.zeros((L, S, r, lin.out_features), dt))
            self._zero_slot[t] = (jnp.zeros((L, lin.in_features, r), dt),
                                  jnp.zeros((L, r, lin.out_features), dt))
        self.scaling = jnp.zeros((S,), jnp.float32)
        # tensor-parallel placements (place_over_mesh): {target: (A, B)}
        # NamedShardings plus one for scaling — None on single-device packs
        self._shardings = None
        self._scaling_sharding = None

    def place_over_mesh(self, mesh, mp_axis="mp", col_targets=None,
                        row_targets=None):
        """Place the pack's slot-stacked factors over a tensor-parallel
        mesh so adapter serving composes with a TP-sharded engine.

        The factors ride the SAME axis split as their base projections
        (models.llama.shard_llama): a COLUMN-parallel target (q/k/v,
        gate_up — output dim sharded) shards ``B [L, S, r, out]`` on its
        out dim and keeps ``A`` replicated, so the delta ``(x A) B`` lands
        sharded exactly like the base projection's output; a ROW-parallel
        target (o_proj, down_proj — input dim sharded) shards
        ``A [L, S, in, r]`` on its in dim and keeps ``B`` replicated, so
        the ``x A`` contraction produces the partial sums GSPMD psums
        where the base row-parallel matmul already does.  ``scaling``
        stays replicated.  Dims the mp axis does not divide fall back to
        replication (adapter factors are small; the mesh lint's
        replicated-giant threshold still applies).

        The shardings are RECORDED and re-applied after every
        ``set_slot`` / ``clear_slot`` scatter, so the swap executables
        and the decode step see ONE argument-sharding signature across
        hot swaps — the zero-recompile contract survives the mesh.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        if col_targets is None or row_targets is None:
            from paddle_tpu.models.llama import (LLAMA_TP_COL_TARGETS,
                                                 LLAMA_TP_ROW_TARGETS)

            col_targets = (LLAMA_TP_COL_TARGETS if col_targets is None
                           else col_targets)
            row_targets = (LLAMA_TP_ROW_TARGETS if row_targets is None
                           else row_targets)
        mesh = getattr(mesh, "jax_mesh", mesh)  # ProcessMesh or jax Mesh
        mp = int(mesh.shape[mp_axis])
        replicated = NamedSharding(mesh, PartitionSpec())
        self._shardings = {}
        for t, (A, B) in self.ab.items():
            a_sh = b_sh = replicated
            if t in row_targets and A.shape[2] % mp == 0:
                a_sh = NamedSharding(
                    mesh, PartitionSpec(None, None, mp_axis, None))
            elif t in col_targets and B.shape[3] % mp == 0:
                b_sh = NamedSharding(
                    mesh, PartitionSpec(None, None, None, mp_axis))
            self._shardings[t] = (a_sh, b_sh)
        self._scaling_sharding = replicated
        self._replace()
        return self

    def _replace(self):
        """Re-commit every pack array to its recorded placement (no-op on
        single-device packs).  Called after construction placement and
        after each slot scatter: the scatter's output sharding is XLA's
        to propagate, and the decode step's zero-recompile contract needs
        the argument shardings bit-stable across swaps."""
        if self._shardings is None:
            return
        for t, (a_sh, b_sh) in self._shardings.items():
            A, B = self.ab[t]
            self.ab[t] = (jax.device_put(A, a_sh), jax.device_put(B, b_sh))
        self.scaling = jax.device_put(self.scaling, self._scaling_sharding)

    @property
    def nbytes(self) -> int:
        return (sum(a.nbytes + b.nbytes for a, b in self.ab.values())
                + self.scaling.nbytes)

    def parts(self):
        """[(name, array)] leaves — the mesh lint's per-leaf walk (same
        contract as ops.paged_attention.pool_parts)."""
        out = [(f"adapter.{t}.{w}", arr)
               for t, (a, b) in sorted(self.ab.items())
               for w, arr in (("A", a), ("B", b))]
        out.append(("adapter.scaling", self.scaling))
        return out

    def set_slot(self, slot, arrays, alpha=None):
        """Install an adapter's stacked arrays into ``slot`` (pure device
        scatter — shapes unchanged).  ``arrays`` is
        ``parse_adapter_state_dict`` output; targets it omits are zeroed
        (the adapter genuinely has no delta there)."""
        slot = int(slot)
        if not 1 <= slot < self.num_slots:
            raise IndexError(
                f"slot {slot} out of range [1, {self.num_slots}) "
                "(slot 0 is the reserved base-model identity)")
        # EVERY target's A and B validated BEFORE any scatter: a shape
        # mismatch surfacing mid-loop would leave the slot half-mutated
        # (old and new weights mixed under one name, epoch already spent)
        for t, (A, B) in self.ab.items():
            if t not in arrays:
                continue
            na, nb = arrays[t]
            want_a = A.shape[0:1] + A.shape[2:]
            want_b = B.shape[0:1] + B.shape[2:]
            if na.shape != want_a or nb.shape != want_b:
                raise ValueError(
                    f"adapter for {t!r} has shapes A{tuple(na.shape)}/"
                    f"B{tuple(nb.shape)}, pack slot expects "
                    f"A{want_a}/B{want_b}")
        for t, (A, B) in self.ab.items():
            if t in arrays:
                na, nb = arrays[t]
                na, nb = na.astype(A.dtype), nb.astype(B.dtype)
            else:
                na, nb = self._zero_slot[t]
            self.ab[t] = (A.at[:, slot].set(na), B.at[:, slot].set(nb))
        a = float(alpha) if alpha is not None else self.alpha
        self.scaling = self.scaling.at[slot].set(a / self.rank)
        self._replace()
        return self

    def clear_slot(self, slot):
        """Zero ``slot`` back to the identity adapter.  Scatters zero
        ARRAYS (not a scalar fill) so the XLA programs are the very ones
        :meth:`set_slot` already compiled — an evict after any install
        costs no fresh compile."""
        slot = int(slot)
        if not 1 <= slot < self.num_slots:
            raise IndexError(f"slot {slot} out of range [1, {self.num_slots})")
        for t, (A, B) in self.ab.items():
            za, zb = self._zero_slot[t]
            self.ab[t] = (A.at[:, slot].set(za), B.at[:, slot].set(zb))
        self.scaling = self.scaling.at[slot].set(0.0)
        self._replace()
        return self


def lora_delta(x, A, B, slots, scaling):
    """The jitted decode step's per-row adapter delta.

    x: ``[B, T, in]`` raw array; A: ``[S, in, r]``; B: ``[S, r, out]``
    (ONE layer's slot-stacked matrices); slots: ``[B]`` int32 slot per
    batch row; scaling: ``[B]`` float32 per-row ``alpha/rank``.  Gathers
    each row's A/B by its slot and returns ``(x @ A_s) @ B_s * s`` in
    ``x``'s dtype.  Slot 0 rows gather zeros — an exact additive identity.
    """
    Ag = jnp.take(A, slots, axis=0)            # [B, in, r]
    Bg = jnp.take(B, slots, axis=0)            # [B, r, out]
    xa = jnp.einsum("bti,bir->btr", x.astype(A.dtype), Ag)
    d = jnp.einsum("btr,bro->bto", xa, Bg)
    return (d * scaling[:, None, None].astype(d.dtype)).astype(x.dtype)


def _make_prefill_hook(pack, target, slot, layer_index):
    A, B = pack.ab[target]
    scale = pack.scaling[slot]

    def hook(_layer, inputs, out):
        li = layer_index()
        x = inputs[0]._value
        d = (x.astype(A.dtype) @ A[li, slot]) @ B[li, slot]
        return Tensor(out._value
                      + (d * scale.astype(d.dtype)).astype(out._value.dtype))

    return hook


@contextlib.contextmanager
def adapter_prefill_scope(layers, pack: AdapterPack, slot: int):
    """Apply ``slot``'s adapter during an EAGER prefill forward.

    Installs forward-post-hooks on every pack target of every decoder
    layer: ``out += (x @ A[l, slot]) @ B[l, slot] * s``.  Works for both
    layer layouts — a LayerList gets per-layer hooks with fixed indices;
    a LayerStack's views all alias ONE template, so its hooks derive the
    layer index from a per-projection call counter (each target fires
    exactly once per layer, in layer order, per forward pass — chunked
    prefill restarts the walk at layer 0 each chunk, which ``% L``
    preserves).  Slot 0 needs no hooks (exact base-model prefill).
    """
    handles = []
    if slot == 0:
        yield
        return
    n = len(layers)
    try:
        if isinstance(layers, LayerStack):
            tpl = layers.__dict__["_template"]
            for t in pack.targets:
                counter = itertools.count()
                handles.append(_resolve_sublayer(tpl, t)
                               .register_forward_post_hook(_make_prefill_hook(
                                   pack, t, slot,
                                   lambda c=counter: next(c) % n)))
        else:
            for li, blk in enumerate(layers):
                for t in pack.targets:
                    handles.append(
                        _resolve_sublayer(blk, t).register_forward_post_hook(
                            _make_prefill_hook(pack, t, slot,
                                               lambda i=li: i)))
        yield
    finally:
        for h in handles:
            h.remove()
