"""paddle.nn.quant — weight-only quantization for serving.

Reference: python/paddle/nn/quant/quantized_linear.py
(weight_quantize/weight_dequantize/weight_only_linear backed by CUDA int8/
int4 GEMM kernels, paddle/phi/kernels/fusion/gpu/...weight_only...).

TPU-native: weights store as int8 (or int4 packed two-per-byte) with
per-output-channel fp scales; the matmul path DEQUANTIZES into the MXU's
native bf16 — on TPU the win is HBM footprint/bandwidth (the usual serving
bottleneck), not integer math, so dequant+matmul IS the fused kernel (XLA
fuses the scale multiply into the matmul epilogue)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.tensor._ops_common import apply, ensure_tensor

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear", "llm_int8_linear"]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight to (quantized, scale-per-out-channel)."""
    x = ensure_tensor(x)
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r}")
    w = x._value.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)  # per-output-channel
    if algo == "weight_only_int4":
        scale = jnp.where(amax > 0, amax / 7.0, 1.0)
        q = jnp.clip(jnp.round(w / scale), -8, 7).astype(jnp.int8)
        # pack two int4 per byte along the input dim
        if q.shape[0] % 2:
            raise ValueError("weight_only_int4 needs an even input dim")
        lo = q[0::2] & 0x0F
        hi = (q[1::2] & 0x0F) << 4
        packed = (lo | hi).astype(jnp.int8)
        return Tensor(packed), Tensor(scale)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return Tensor(q), Tensor(scale)


def _unpack_int4(packed):
    lo = (packed & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo)  # sign-extend nibble
    hi = ((packed >> 4) & 0x0F).astype(jnp.int8)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=1).reshape((-1,) + packed.shape[1:])
    return out


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    x, scale = ensure_tensor(x), ensure_tensor(scale)

    def _dq(q, s):
        qv = _unpack_int4(q) if algo == "weight_only_int4" else q
        return qv.astype(jnp.float32) * s.astype(jnp.float32)

    return apply("weight_dequantize", _dq, x, scale)


def weight_only_linear(x, weight, bias=None, weight_scale=None, weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias — reference weight_only_linear.

    weight: int8 [in, out] or int4-packed [in//2, out]; weight_scale: [out].
    The dequantized operand feeds the MXU in the activation dtype; XLA fuses
    the per-channel scale into the matmul epilogue.
    """
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    weight_scale = ensure_tensor(weight_scale)
    extras = [ensure_tensor(bias)] if bias is not None else []

    def _fn(xv, qw, s, *rest):
        qv = _unpack_int4(qw) if weight_dtype == "int4" else qw
        w = (qv.astype(jnp.float32) * s.astype(jnp.float32)).astype(xv.dtype)
        out = jnp.matmul(xv, w)
        if rest:
            out = out + rest[0]
        return out

    return apply("weight_only_linear", _fn, x, weight, weight_scale, *extras)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """Reference llm_int8_linear: on TPU the outlier-split scheme degenerates
    to the same dequant-into-bf16 matmul (no int8 tensor cores to protect),
    so this is weight_only_linear with the llm.int8 quantization layout."""
    return weight_only_linear(x, weight, bias=bias, weight_scale=weight_scale, weight_dtype="int8")
