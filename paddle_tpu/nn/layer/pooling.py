"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

import paddle_tpu.nn.functional as F
from .layers import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
    "LPPool1D", "LPPool2D",
]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, name=None, **kw):
        super().__init__()
        self.kernel_size, self.stride, self.padding, self.ceil_mode = kernel_size, stride, padding, ceil_mode

    def forward(self, x):
        return getattr(F, self._fn)(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class MaxPool1D(_Pool):
    _fn = "max_pool1d"

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    _fn = "max_pool2d"

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class MaxPool3D(_Pool):
    _fn = "max_pool3d"

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding, ceil_mode=self.ceil_mode)


class _AdaptivePool(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.lp_pool2d(x, *self.args)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os = self.args
        return F.max_unpool1d(x, indices, k, s, p, df, os)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os = self.args
        return F.max_unpool2d(x, indices, k, s, p, df, os)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os = self.args
        return F.max_unpool3d(x, indices, k, s, p, df, os)

__all__ += ['MaxUnPool1D', 'MaxUnPool2D', 'MaxUnPool3D']
