"""Layer base class.

Capability parity with the reference's `paddle.nn.Layer`
(python/paddle/nn/layer/layers.py:331): parameter/buffer/sublayer registries,
forward hooks, state_dict round-trip, train/eval modes, dtype moves.  No
device moves exist here — placement is owned by jax.sharding at the training
step level, which is the TPU-native replacement for per-layer `.to(device)`.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np
import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype, to_paddle_dtype
from paddle_tpu._core.tensor import Parameter, Tensor

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """Parameter attribute bundle (reference python/paddle/base/param_attr.py)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        do_model_average: bool = True,
        need_clip: bool = True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # an initializer instance
        return ParamAttr(initializer=attr)


class _HookHandle:
    _next_id = [0]

    def __init__(self, registry: dict):
        self._registry = registry
        self.hook_id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1

    def remove(self):
        self._registry.pop(self.hook_id, None)


class Layer:
    """Base of all network layers (reference nn.Layer semantics)."""

    def __init__(self, name_scope: str | None = None, dtype: str = "float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: dict = collections.OrderedDict()
        self._forward_post_hooks: dict = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._init_in_dynamic_mode = True

    # ------------------------------------------------------------- registry
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer) and layers is not None:
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
                if isinstance(value, Tensor):
                    params[name].set_value(value)
                    return
                params.pop(name)
            if layers is not None and name in layers and not isinstance(value, Layer):
                layers.pop(name)
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    if value is None:
                        buffers.pop(name)
                        object.__setattr__(self, name, None)
                    else:
                        buffers[name] = value
                    return
                buffers.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # -------------------------------------------------------------- creation
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Parameter:
        from paddle_tpu.nn import initializer as I

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        # precedence: explicit ParamAttr > set_global_initializer > layer default
        init = attr.initializer or I._default_init(is_bias) or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        value = init._init_value(tuple(int(s) for s in shape), to_jax_dtype(dtype))
        p = Parameter(value, trainable=attr.trainable, name=attr.name or "")
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name: str, parameter: Parameter | None):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        if parameter is None:
            self._parameters.pop(name, None)
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor, persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        elif tensor is not None:
            tensor.persistable = True
        return tensor

    # ------------------------------------------------------------ iteration
    def parameters(self, include_sublayers: bool = True) -> list:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> list:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self: bool = False) -> list:
        out = []
        for name, l in self._traverse("", True):
            if name == "" and not include_self:
                continue
            out.append(l)
        return out

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        for name, l in self._traverse(prefix, True):
            if name == prefix and not include_self:
                continue
            yield name, l

    def _traverse(self, prefix: str, include_sublayers: bool):
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------------------------------------------------------ state dict
    def state_dict(
        self,
        destination=None,
        include_sublayers: bool = True,
        structured_name_prefix: str = "",
        use_hook: bool = True,
    ) -> dict:
        out = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix, include_sublayers):
            out[name] = p
        for name, b in self.named_buffers(structured_name_prefix, include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self._locate(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            out[name] = b
        return out

    def _locate(self, qualified: str):
        parts = qualified.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict: dict, use_structured_name: bool = True,
                       allow_partial: bool = False):
        """Load ``state_dict`` into this layer; returns
        ``(missing, unexpected)`` key lists.

        ``allow_partial=True`` is the documented PARTIAL-load path for
        subset checkpoints — e.g. an adapter-only LoRA state dict
        (``nn.lora.lora_state_dict``) loading into a full model: missing
        own keys are expected and tolerated silently, but UNEXPECTED
        checkpoint keys still raise — a key this model cannot place is a
        wrong checkpoint, not a smaller one.  The default (False) keeps
        the exact historical contract: nothing raises, callers inspect
        the returned lists."""
        own = self.state_dict()
        if any(name not in state_dict for name in own):
            # stacked (LayerStack) vs per-layer decoder layouts interconvert
            # so checkpoints survive flipping fuse_layer_stack; skipped
            # entirely on the common exact-match path
            from .stack import adapt_state_dict

            state_dict = adapt_state_dict(self, state_dict, own=own)
        unexpected = [name for name in state_dict if name not in own]
        if allow_partial and unexpected:
            # checked BEFORE any load: a wrong checkpoint must not leave
            # the model half-mutated
            raise ValueError(
                "set_state_dict(allow_partial=True): checkpoint holds "
                f"{len(unexpected)} key(s) this layer cannot place, e.g. "
                f"{unexpected[:3]} — partial load tolerates MISSING keys, "
                "never unknown ones")
        missing = []
        for name, t in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src._value if isinstance(src, Tensor) else jnp.asarray(np.asarray(src))
                # copy-by-value (paddle assign semantics): sharing the source
                # array would alias it into this layer, and a donated compiled
                # step (TrainStep) would delete it out from under the source
                t.set_value(jnp.copy(arr))
            else:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ----------------------------------------------------------------- modes
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ------------------------------------------------------------------ util
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = to_jax_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._bind(p._value.astype(dt))
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._bind(b._value.astype(dt))
            self._dtype = to_paddle_dtype(dtype).name
        return self

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"

    # ------------------------------------------------------------------ call
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.hook_id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.hook_id] = hook
        return handle

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
