"""Stacked-layer scan engine: depth-constant trace and compile.

A Python ``for`` loop over N homogeneous decoder blocks traces and compiles
each block separately, so HLO size, trace time and XLA compile time grow
linearly with depth — a 32-layer LLaMA pays ~32x the compile of one block
and every process start recompiles from scratch.  ``LayerStack`` stacks the
parameters of N identical blocks along a new leading axis and executes the
stack as ONE ``jax.lax.scan`` whose body is the block traced once: the
program XLA sees is O(1) in depth ("Operator Fusion in XLA" shows fusion
works best over compact programs; MaxText/praxis use the same scan-over-
layers layout at scale).

Differentiability rides the `apply` funnel exactly like ``dy2static_run``:
the whole scan is one taped op, jax.vjp supplies the backward (scan
transposes to a reverse scan), and stacked-parameter grads accumulate into
the stacked Parameters so optimizers need no changes.

Recompute tiers (the reference's ``recompute_granularity``, PaddleNLP
llama modeling.py) are implemented with ``jax.checkpoint`` inside the scan
body:

- ``"full"``       — the body is wrapped in plain ``jax.checkpoint``
  (``nothing_saveable``): backward recomputes the whole block from its
  carry input.
- ``"full_attn"``  — no body-level checkpoint; cooperative blocks consult
  :func:`current_recompute_tier` and run their attention sublayer under
  ``fleet.recompute`` (a nested ``jax.checkpoint``), so exactly the
  attention sublayer recomputes while MLP/norm residuals stay saved
  (``LlamaDecoderLayer`` does this).
- ``"core_attn"``  — no body-level checkpoint; the core softmax(qk)v runs
  under its own ``jax.checkpoint`` (``scaled_dot_product_attention``
  consults the tier), so only the attention probabilities rematerialize.

Checkpoint-layout compatibility: state_dict keys for a stack at path ``P``
are ``P.<template key>`` with a leading ``[N, ...]`` axis, vs the unstacked
``P.<i>.<template key>``.  :func:`adapt_state_dict` converts either
direction against a target model (hooked into ``Layer.set_state_dict``), so
existing per-layer checkpoints load into scan models and scan checkpoints
load into loop models.
"""

from __future__ import annotations

import contextlib
import re
import threading

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Parameter, Tensor

from .layers import Layer

__all__ = [
    "LayerStack",
    "adapt_state_dict",
    "stack_state_dict",
    "unstack_state_dict",
    "current_recompute_tier",
    "recompute_tier_scope",
]

RECOMPUTE_TIERS = (None, "full", "full_attn", "core_attn")


class _TierState(threading.local):
    def __init__(self):
        self.tier = None


_tier_state = _TierState()


def current_recompute_tier():
    """The active recompute granularity (None outside a tier scope).
    Consulted by cooperative layers: ``scaled_dot_product_attention`` wraps
    its core in jax.checkpoint under 'core_attn'; blocks implement
    'full_attn' themselves by running their attention sublayer under
    ``fleet.recompute`` (see LlamaDecoderLayer)."""
    return _tier_state.tier


@contextlib.contextmanager
def recompute_tier_scope(tier):
    """Install a recompute granularity for the enclosed forward (used by
    LayerStack's scan body and by models running the unrolled loop with a
    sub-layer granularity)."""
    if tier not in RECOMPUTE_TIERS:
        raise ValueError(
            f"recompute granularity must be one of {RECOMPUTE_TIERS}, got {tier!r}")
    prev = _tier_state.tier
    _tier_state.tier = tier
    try:
        yield
    finally:
        _tier_state.tier = prev


def _is_stochastic(layer) -> bool:
    """Heuristic for blocks that draw training-time randomness: Dropout-type
    sublayers, or any sublayer carrying a positive dropout rate attribute
    (MultiHeadAttention stores `dropout` and calls functional dropout with
    no Dropout sublayer).  A baked key inside the scan body would reuse ONE
    mask across every layer and step, so err toward threading keys."""
    name = type(layer).__name__
    if "Dropout" in name:
        return True
    for attr in ("dropout", "dropout_p", "drop_rate"):
        v = getattr(layer, attr, None)
        if isinstance(v, (int, float)) and v > 0:
            return True
    return False


def _body_wrapper(tier):
    """The scan-body jax.checkpoint wrapper for a tier (None = identity).
    full_attn / core_attn remat inside the block itself (nested checkpoint
    engaged via the tier scope), so the body saves normally there."""
    if tier == "full":
        return jax.checkpoint
    return lambda f: f


class LayerStack(Layer):
    """Stack N homogeneous blocks into scanned, stacked-parameter form.

    ``forward(h, *args, **kwargs)`` threads ``h`` as the scan carry through
    every block; ``*args``/``**kwargs`` broadcast unchanged to each block
    (non-Tensor args and all kwargs are static).  Each block must return a
    single Tensor of ``h``'s shape.

    Iteration/indexing yield a per-layer *view*: the template block with
    tape-recorded slices of the stacked parameters bound in — so per-layer
    code paths (KV-cache decode, tensor-parallel placement walks,
    ``context_parallel_llama``) keep working; grads through a view flow
    into the stacked Parameters.  ALL views alias ONE template object and
    each ``stack[i]`` rebinds it in place: consume a view before taking the
    next (``for blk in stack: blk(...)``), never materialize several at
    once — ``list(stack)`` yields N references that all hold the LAST
    layer's weights.  (Attribute writes on a view, e.g. setting a mode
    flag, intentionally reach every layer — the shared-template contract
    context_parallel_llama uses.)

    ``recompute`` selects the granularity tier (see module docstring);
    ``needs_rng`` threads a distinct per-layer PRNG key through the scan
    body (auto-detected from Dropout sublayers) so stochastic blocks draw
    per-layer randomness instead of a frozen key.
    """

    def __init__(self, layers, recompute=None, needs_rng=None):
        super().__init__()
        layers = list(layers)
        if not layers:
            raise ValueError("LayerStack needs at least one layer")
        if recompute not in RECOMPUTE_TIERS:
            raise ValueError(
                f"recompute must be one of {RECOMPUTE_TIERS}, got {recompute!r}")
        template = layers[0]
        sds = [l.state_dict() for l in layers]
        ref_sd = sds[0]
        ref_struct = {k: (tuple(v._value.shape), str(v._value.dtype))
                      for k, v in ref_sd.items()}
        for i, (l, sd) in enumerate(zip(layers[1:], sds[1:]), 1):
            if type(l) is not type(template):
                raise TypeError(
                    f"LayerStack blocks must be homogeneous: block 0 is "
                    f"{type(template).__name__}, block {i} is {type(l).__name__}")
            struct = {k: (tuple(v._value.shape), str(v._value.dtype))
                      for k, v in sd.items()}
            if struct != ref_struct:
                raise ValueError(
                    f"LayerStack blocks must share one parameter structure; "
                    f"block {i} differs from block 0")
        # the template is a binding slot, NOT a sublayer: its own parameters
        # are shadowed by the stacked ones and must stay out of state_dict()
        self.__dict__["_template"] = template
        self._num_layers = len(layers)
        self._recompute = recompute

        param_names = {n for n, _ in template.named_parameters()}
        self._param_keys, self._buffer_keys = [], []
        for key in ref_sd:
            stacked = jnp.stack([sd[key]._value for sd in sds])
            if key in param_names:
                src = dict(template.named_parameters())[key]
                p = Parameter(stacked, trainable=not src.stop_gradient)
                self.add_parameter(key, p)
                self._param_keys.append(key)
            else:
                self.register_buffer(key, Tensor(stacked))
                self._buffer_keys.append(key)
        self._stack_keys = self._param_keys + self._buffer_keys
        # template-side binding slots, resolved once: (registry dict, name)
        self._slots = {}
        for key in self._stack_keys:
            owner = template
            *path, short = key.split(".")
            for part in path:
                owner = owner._sub_layers[part]
            reg = owner._parameters if short in owner._parameters else owner._buffers
            self._slots[key] = (reg, short)
        if needs_rng is None:
            needs_rng = any(_is_stochastic(l)
                            for l in template.sublayers(include_self=True))
        self._needs_rng = bool(needs_rng)

    # ------------------------------------------------------------ inspection
    @property
    def num_layers(self) -> int:
        return self._num_layers

    def stack_keys(self):
        """Per-layer template state keys, in stacked-state order."""
        return list(self._stack_keys)

    def __len__(self):
        return self._num_layers

    def _stacked_tensor(self, key):
        return (self._parameters[key] if key in self._parameters
                else self._buffers[key])

    def _bind_view(self, i):
        if not -self._num_layers <= i < self._num_layers:
            raise IndexError(f"layer index {i} out of range [0, {self._num_layers})")
        i = i % self._num_layers
        self._sync_template_mode()
        for key in self._stack_keys:
            reg, short = self._slots[key]
            reg[short] = self._stacked_tensor(key)[i]
        return self.__dict__["_template"]

    def __getitem__(self, i):
        return self._bind_view(i)

    def __iter__(self):
        for i in range(self._num_layers):
            yield self._bind_view(i)

    # -------------------------------------------------------------- forward
    def _sync_template_mode(self):
        # train()/eval() walk registered sublayers setting .training — the
        # hidden template is invisible to that walk, so mirror the stack's
        # mode onto it here (forward and view paths both call this)
        tpl = self.__dict__["_template"]
        if tpl.training != self.training:
            tpl.train() if self.training else tpl.eval()

    def forward(self, h, *args, **kwargs):
        from paddle_tpu.tensor._ops_common import apply

        self._sync_template_mode()

        if not isinstance(h, Tensor):
            h = Tensor(jnp.asarray(h))
        for k, v in kwargs.items():
            if isinstance(v, Tensor):
                raise TypeError(
                    f"LayerStack broadcast kwargs must be static; pass "
                    f"Tensor {k!r} positionally")
        tensor_pos = tuple(i for i, a in enumerate(args) if isinstance(a, Tensor))
        tensor_args = [args[i] for i in tensor_pos]
        statics = tuple((i, a) for i, a in enumerate(args)
                        if not isinstance(a, Tensor))
        state = [self._stacked_tensor(k) for k in self._stack_keys]
        extra = []
        if self._needs_rng and self.training:
            from paddle_tpu._core import random as rng_mod

            # raw (non-Tensor) arg: concrete in eager, a traced key inside
            # TrainStep/jit — either way split per layer inside the scan
            extra = [rng_mod.next_key()]
        return apply(
            "layer_stack_scan", self._scan_raw, *state, h, *tensor_args, *extra,
            _tensor_pos=tensor_pos, _statics=statics, _n_args=len(args),
            _kw=tuple(sorted(kwargs.items())), _has_key=bool(extra),
            _training=self.training,
        )

    def _scan_raw(self, *vals, _tensor_pos, _statics, _n_args, _kw, _has_key,
                  _training):
        """Raw scan body host fn (runs under the funnel's jax.vjp / jit
        trace).  A bound method so the dispatch cache can key it by
        (code, self): steady-state eager steps reuse one cached
        forward+pullback trace for the whole stack."""
        n_state = len(self._stack_keys)
        state_vals = list(vals[:n_state])
        carry0 = vals[n_state]
        rest = list(vals[n_state + 1:])
        base_key = rest.pop() if _has_key else None
        template = self.__dict__["_template"]
        slots = [self._slots[k] for k in self._stack_keys]
        kwargs = dict(_kw)
        from paddle_tpu._core import autograd as core_ag
        from paddle_tpu._core import random as rng_mod

        def body(carry, xs):
            slices, key = xs
            originals = [reg[short] for reg, short in slots]
            try:
                for (reg, short), v in zip(slots, slices):
                    reg[short] = Tensor(v)
                full = [None] * _n_args
                for i, a in _statics:
                    full[i] = a
                for i, v in zip(_tensor_pos, rest):
                    full[i] = Tensor(v)
                key_ctx = (rng_mod.key_scope(key) if key is not None
                           else contextlib.nullcontext())
                with key_ctx, core_ag.no_grad(), \
                        recompute_tier_scope(self._recompute):
                    out = template(Tensor(carry), *full, **kwargs)
                if not isinstance(out, Tensor):
                    raise TypeError(
                        "LayerStack blocks must return a single Tensor "
                        f"carry; got {type(out).__name__}")
                return out._value, None
            finally:
                for (reg, short), v in zip(slots, originals):
                    reg[short] = v

        body = _body_wrapper(self._recompute)(body)
        xs_keys = (jax.random.split(base_key, self._num_layers)
                   if base_key is not None else None)
        carry, _ = jax.lax.scan(
            body, carry0, (tuple(state_vals), xs_keys))
        return carry

    # ------------------------------------------------------- decode scan
    def decode_scan(self, body, h, k_state, v_state, extra=None):
        """Scan the stack ONCE over stacked per-layer KV state (the paged
        decode tier): ``body(layer, h, kc, vc) -> (h, kc, vc)`` is the
        per-layer decode step (e.g. ``models.llama._decode_layer_paged``
        with the broadcast args closed over); ``h`` is the Tensor carry;
        ``k_state``/``v_state`` are raw arrays with a leading layer axis
        ``[N, ...]`` riding the scan as xs/ys.  Returns
        ``(h, new_k_state, new_v_state)`` in the same stacked layout.

        ``extra``: an optional READ-ONLY pytree of per-layer state — every
        leaf carries the same leading ``[N, ...]`` layer axis and rides
        the scan as additional xs (sliced per layer, never returned as
        ys).  When given, the body takes a fourth argument:
        ``body(layer, h, kc, vc, extra_slice)``.  The multi-tenant LoRA
        AdapterPack threads its slot-stacked A/B matrices through here
        (nn/lora.py, docs/LORA.md).

        This is the serving-side counterpart of :meth:`forward`: the paged
        KV pools thread through the scan as per-layer state, so a decode
        step program traces and XLA-compiles ONE layer body regardless of
        depth.  Inference-only — it runs under ``no_grad`` inside the
        caller's jitted step (decode never differentiates), so it skips
        the ``apply`` funnel and recompute tiers entirely.
        """
        from paddle_tpu._core import autograd as core_ag

        self._sync_template_mode()
        template = self.__dict__["_template"]
        slots = [self._slots[k] for k in self._stack_keys]
        state_vals = [self._stacked_tensor(k)._value
                      for k in self._stack_keys]
        if not isinstance(h, Tensor):
            h = Tensor(jnp.asarray(h))
        has_extra = extra is not None

        def scan_body(carry, xs):
            if has_extra:
                slices, kc, vc, ex = xs
            else:
                slices, kc, vc = xs
            originals = [reg[short] for reg, short in slots]
            try:
                for (reg, short), v in zip(slots, slices):
                    reg[short] = Tensor(v)
                with core_ag.no_grad():
                    if has_extra:
                        out, kc, vc = body(template, Tensor(carry), kc, vc, ex)
                    else:
                        out, kc, vc = body(template, Tensor(carry), kc, vc)
                if not isinstance(out, Tensor):
                    raise TypeError(
                        "decode_scan body must return (Tensor, kc, vc); "
                        f"got {type(out).__name__} carry")
                return out._value, (kc, vc)
            finally:
                for (reg, short), v in zip(slots, originals):
                    reg[short] = v

        xs = ((tuple(state_vals), k_state, v_state, extra) if has_extra
              else (tuple(state_vals), k_state, v_state))
        carry, (new_k, new_v) = jax.lax.scan(scan_body, h._value, xs)
        return Tensor(carry), new_k, new_v


def shard_stacked_params(stack: "LayerStack", mesh, place_fn, col_keys,
                         row_keys):
    """Megatron TP placement over a LayerStack's stacked weights.

    The layer axis is axis 0, so relative to per-layer placement everything
    shifts right one: column-parallel weights [N, in, out] shard axis 2 and
    their biases [N, out] axis 1; row-parallel weights shard axis 1.
    ``place_fn(shard_axis)`` builds the full placement list (the caller owns
    the mesh-axis bookkeeping); ``col_keys``/``row_keys`` are sublayer paths
    relative to the block (e.g. "self_attn.q_proj")."""
    from paddle_tpu.distributed.auto_parallel import Shard, shard_tensor

    for key, p in list(stack._parameters.items()):
        prefix, _, leaf = key.rpartition(".")
        placement = None
        if prefix in col_keys:
            placement = Shard(2) if leaf == "weight" else Shard(1)
        elif prefix in row_keys and leaf == "weight":
            placement = Shard(1)
        if placement is not None:
            stack._parameters[key] = shard_tensor(
                p, mesh, place_fn(placement), stop_gradient=p.stop_gradient)
    return stack


# ------------------------------------------------------- layout converters


def stack_state_dict(state_dict: dict, prefix: str, num_layers: int,
                     keys=None) -> dict:
    """Convert ``{prefix}.{i}.{key}`` per-layer entries into one stacked
    ``{prefix}.{key}`` entry each (leading axis = layer).  Non-matching
    entries pass through untouched."""
    out = dict(state_dict)
    pre = f"{prefix}." if prefix else ""  # prefix "" = the stack IS the root
    if keys is None:
        pat = re.compile(re.escape(pre) + r"0\.(.+)$")
        keys = [m.group(1) for k in state_dict if (m := pat.match(k))]
    for key in keys:
        per_layer = [f"{pre}{i}.{key}" for i in range(num_layers)]
        if not all(p in state_dict for p in per_layer):
            continue
        vals = []
        for p in per_layer:
            v = out.pop(p)
            vals.append(v._value if isinstance(v, Tensor) else jnp.asarray(v))
        out[f"{pre}{key}"] = Tensor(jnp.stack(vals))
    return out


def unstack_state_dict(state_dict: dict, prefix: str, num_layers: int,
                       keys) -> dict:
    """Inverse of :func:`stack_state_dict`: split ``{prefix}.{key}`` stacked
    entries back into ``{prefix}.{i}.{key}`` per-layer entries."""
    out = dict(state_dict)
    pre = f"{prefix}." if prefix else ""
    for key in keys:
        name = f"{pre}{key}"
        if name not in state_dict:
            continue
        v = out.pop(name)
        arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
        if arr.shape[0] != num_layers:
            raise ValueError(
                f"stacked entry {name!r} has leading dim {arr.shape[0]}, "
                f"expected {num_layers}")
        for i in range(num_layers):
            out[f"{pre}{i}.{key}"] = Tensor(arr[i])
    return out


def adapt_state_dict(model: Layer, state_dict: dict, own=None) -> dict:
    """Convert a checkpoint between stacked and unstacked decoder layouts to
    match ``model``'s own layout (no-op when layouts already agree).

    Both directions are driven by the model: a LayerStack at path P stacks
    matching ``P.{i}.{key}`` checkpoint entries; a per-layer stack of keys
    ``P.{i}.{key}`` in the model unstacks a matching ``P.{key}`` entry whose
    leading dim equals the layer count.  ``own`` lets the caller reuse an
    already-built ``model.state_dict()``.
    """
    out = state_dict
    # stacked model <- unstacked checkpoint (include_self: the stack may BE
    # the root model being loaded, with path "")
    for path, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, LayerStack):
            pre = f"{path}." if path else ""
            missing = [k for k in sub.stack_keys()
                       if f"{pre}{k}" not in state_dict]
            if missing and f"{pre}0.{missing[0]}" in state_dict:
                out = stack_state_dict(out, path, len(sub), sub.stack_keys())
    # unstacked model <- stacked checkpoint
    if own is None:
        own = model.state_dict()
    pat = re.compile(r"^(.*?)\.(\d+)\.(.+)$")
    groups: dict = {}
    for name in own:
        m = pat.match(name)
        if m:
            prefix, idx, key = m.group(1), int(m.group(2)), m.group(3)
            g = groups.setdefault((prefix, key), set())
            g.add(idx)
    for (prefix, key), idxs in groups.items():
        n = len(idxs)
        if idxs != set(range(n)):
            continue
        stacked_name = f"{prefix}.{key}"
        if stacked_name in out and f"{prefix}.0.{key}" not in out:
            v = out[stacked_name]
            arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if arr.ndim and arr.shape[0] == n:
                out = unstack_state_dict(out, prefix, n, [key])
    return out
