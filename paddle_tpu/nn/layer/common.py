"""Common layers (reference: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

import paddle_tpu.nn.functional as F
from paddle_tpu.nn import initializer as I
from .layers import Layer, ParamAttr

__all__ = [
    "Identity",
    "Linear",
    "Embedding",
    "Dropout",
    "Dropout2D",
    "Dropout3D",
    "AlphaDropout",
    "Flatten",
    "Upsample",
    "UpsamplingBilinear2D",
    "UpsamplingNearest2D",
    "Pad1D",
    "Pad2D",
    "Pad3D",
    "ZeroPad2D",
    "CosineSimilarity",
    "Bilinear",
    "Unfold",
    "Fold",
    "PixelShuffle",
    "PixelUnshuffle",
    "ChannelShuffle",
]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (reference layout)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=I.XavierNormal()
        )
        self.bias = (
            self.create_parameter([out_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._sparse = bool(sparse)
        self._padding_idx = (
            None if padding_idx is None else (padding_idx if padding_idx >= 0 else num_embeddings + padding_idx)
        )
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr, default_initializer=I.XavierNormal()
        )
        if self._padding_idx is not None:
            import jax.numpy as jnp

            self.weight._bind(self.weight._value.at[self._padding_idx].set(jnp.zeros((embedding_dim,), self.weight._value.dtype)))

    def forward(self, x):
        if not self._sparse:
            return F.embedding(x, self.weight, padding_idx=self._padding_idx)
        return self._sparse_forward(x)

    def _sparse_forward(self, x):
        """sparse=True (reference lookup_table sparse-grad branch): the
        lookup runs on a DETACHED weight, and an output hook turns the
        incoming cotangent into a SelectedRows gradient — the dense [V, H]
        gradient is never materialized; the optimizer applies the lazy
        row update (framework/selected_rows.py)."""
        import jax.numpy as jnp

        from paddle_tpu._core.autograd import is_grad_enabled
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.framework.selected_rows import SelectedRows

        w = self.weight
        detached = Tensor(w._value, stop_gradient=True)
        out = F.embedding(x, detached, padding_idx=self._padding_idx)
        if w.stop_gradient or not is_grad_enabled():
            return out

        # the lookup ran on a detached weight, so `out` is off the tape;
        # a zero-valued scalar anchor re-attaches it (its own grad is a
        # throwaway scalar) so the output hook below receives the cotangent
        from paddle_tpu._core.autograd import apply

        anchor = Tensor(jnp.zeros((), out._value.dtype), stop_gradient=False)
        out = apply("sparse_embedding", lambda o, a: o + a, out, anchor)

        ids = (x._value if isinstance(x, Tensor) else jnp.asarray(x)).reshape(-1)
        H = self._embedding_dim
        pad = self._padding_idx

        def hook(g):
            vals = g._value.reshape(-1, H)
            if pad is not None:
                vals = jnp.where((ids == pad)[:, None], jnp.zeros((), vals.dtype), vals)
            sr = SelectedRows(ids, vals, self._num_embeddings)
            if w.grad is None:
                w.grad = sr
            elif isinstance(w.grad, SelectedRows):
                w.grad = w.grad.accumulate(sr)
            else:
                w.grad = Tensor(w.grad._value + sr.to_dense())
            return g

        out.register_hook(hook)
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr, default_initializer=I.XavierNormal()
        )
        self.bias = (
            self.create_parameter([out_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unflatten(Layer):
    """paddle.nn.Unflatten parity (reference python/paddle/nn/layer/common.py)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import unflatten

        return unflatten(x, self.axis, self.shape)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)

__all__ += ['Unflatten', 'PairwiseDistance']
