"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn import initializer as I
from .layers import Layer

__all__ = [
    "LayerNorm",
    "RMSNorm",
    "BatchNorm",
    "BatchNorm1D",
    "BatchNorm2D",
    "BatchNorm3D",
    "SyncBatchNorm",
    "GroupNorm",
    "InstanceNorm1D",
    "InstanceNorm2D",
    "InstanceNorm3D",
    "LocalResponseNorm",
    "SpectralNorm",
]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._normalized_shape = (
            [normalized_shape] if isinstance(normalized_shape, int) else list(normalized_shape)
        )
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter(self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)


class RMSNorm(Layer):
    """LLaMA-family RMS norm (reference exposes fused_rms_norm in incubate)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        from paddle_tpu import ops as _ops

        if _ops.use_pallas():
            import paddle_tpu.incubate.nn.functional as _FF

            return _FF.fused_rms_norm(x, self.weight, epsilon=self._epsilon)
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = (
            self.create_parameter([num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter([num_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            self.weight,
            self.bias,
            training=self.training,
            momentum=self._momentum,
            epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.  Under SPMD (shard_tensor/pjit) XLA computes
    global batch stats automatically when the batch axis is sharded — so the
    layer is numerically the plain BatchNorm here; the sync happens in the
    partitioner (GSPMD), not in the layer (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm uses a NCCL allreduce kernel).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            self.create_parameter([num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter([num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter([num_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )
        self._data_format = data_format

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral norm via power iteration (reference nn.SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter([h], default_initializer=I.Normal(0, 1))
        self.weight_v = self.create_parameter([w], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from paddle_tpu.tensor._ops_common import apply

        dim, eps, iters = self._dim, self._epsilon, self._power_iters

        def _sn(w, u, v):
            perm = [dim] + [d for d in range(w.ndim) if d != dim]
            mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return apply("spectral_norm", _sn, weight, self.weight_u, self.weight_v)
