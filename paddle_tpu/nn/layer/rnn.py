"""RNN layers (reference: python/paddle/nn/layer/rnn.py).

Time recursion runs under jax.lax.scan — the compiler-friendly control-flow
replacement for the reference's cudnn RNN kernels / per-step Python loops.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn import initializer as I
from paddle_tpu.tensor._ops_common import apply, ensure_tensor
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN", "LSTM", "GRU", "BiRNN"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32", init_value=0.0):
        batch = batch_ref.shape[0]
        return paddle.full([batch, self.hidden_size], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _cell(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply("simple_rnn_cell", _cell, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def _cell(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply("lstm_cell", _cell, inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        h = apply("gru_cell", _cell, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell into a scan over time (reference nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outputs = []
        x = inputs
        if not self.time_major:
            x = paddle.transpose(x, [1, 0] + list(range(2, x.ndim)))
        steps = range(x.shape[0] - 1, -1, -1) if self.is_reverse else range(x.shape[0])
        states = initial_states
        outs = [None] * x.shape[0]
        for t in steps:
            out, states = self.cell(x[t], states)
            outs[t] = out
        stacked = paddle.stack(outs, axis=0)
        if not self.time_major:
            stacked = paddle.transpose(stacked, [1, 0] + list(range(2, stacked.ndim)))
        return stacked, states


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrent network executed as a
    fused lax.scan per layer/direction — weights stacked so each time step is
    one batched matmul on the MXU."""

    mode = "RNN_TANH"

    def __init__(
        self,
        input_size,
        hidden_size,
        num_layers=1,
        direction="forward",
        time_major=False,
        dropout=0.0,
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        name=None,
    ):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        self.num_directions = num_dirs
        gate_mult = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}[self.mode]
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                suffix = f"_reverse" if d == 1 else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size, in_sz], weight_ih_attr, default_initializer=init),
                )
                self.add_parameter(
                    f"weight_hh_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size, hidden_size], weight_hh_attr, default_initializer=init),
                )
                self.add_parameter(
                    f"bias_ih_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init),
                )
                self.add_parameter(
                    f"bias_hh_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init),
                )

    def _step_fn(self):
        mode = self.mode

        def step(carry, xt, wi, wh, bi, bh):
            if mode == "LSTM":
                h, c = carry
                gates = xt @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return (h_new, c_new), h_new
            if mode == "GRU":
                h = carry
                gi = xt @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                return (1 - z) * c + z * h, (1 - z) * c + z * h
            h = carry
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
            h_new = act(xt @ wi.T + bi + h @ wh.T + bh)
            return h_new, h_new

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.mode == "LSTM"
        num_dirs = self.num_directions
        step = self._step_fn()

        params = []
        for layer in range(self.num_layers):
            for d in range(num_dirs):
                suffix = "_reverse" if d == 1 else ""
                params.append(
                    (
                        getattr(self, f"weight_ih_l{layer}{suffix}"),
                        getattr(self, f"weight_hh_l{layer}{suffix}"),
                        getattr(self, f"bias_ih_l{layer}{suffix}"),
                        getattr(self, f"bias_hh_l{layer}{suffix}"),
                    )
                )

        time_major = self.time_major
        num_layers = self.num_layers
        hidden = self.hidden_size
        mode = self.mode

        def _run(x, *flat_params):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
            B = x.shape[1]
            hs, cs = [], []
            inp = x
            idx = 0
            for layer in range(num_layers):
                outs_dir = []
                for d in range(num_dirs):
                    wi, wh, bi, bh = flat_params[idx * 4 : idx * 4 + 4]
                    idx += 1
                    h0 = jnp.zeros((B, hidden), x.dtype)
                    carry0 = (h0, jnp.zeros((B, hidden), x.dtype)) if is_lstm else h0
                    seq = jnp.flip(inp, 0) if d == 1 else inp

                    def scan_step(carry, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                        return step(carry, xt, wi, wh, bi, bh)

                    carry_f, out = jax.lax.scan(scan_step, carry0, seq)
                    if d == 1:
                        out = jnp.flip(out, 0)
                    outs_dir.append(out)
                    if is_lstm:
                        hs.append(carry_f[0])
                        cs.append(carry_f[1])
                    else:
                        hs.append(carry_f)
                inp = jnp.concatenate(outs_dir, axis=-1) if num_dirs == 2 else outs_dir[0]
            out = inp if time_major else jnp.swapaxes(inp, 0, 1)
            h_stack = jnp.stack(hs, axis=0)
            if is_lstm:
                c_stack = jnp.stack(cs, axis=0)
                return out, h_stack, c_stack
            return out, h_stack

        flat = [p for group in params for p in group]
        result = apply("rnn", _run, ensure_tensor(inputs), *flat)
        if is_lstm:
            out, h, c = result
            return out, (h, c)
        out, h = result
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh", **kw):
        self.mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction, time_major, dropout, **kw)


class LSTM(_RNNBase):
    mode = "LSTM"


class GRU(_RNNBase):
    mode = "GRU"


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, st_fw = self.fw(inputs, states_fw)
        out_bw, st_bw = self.bw(inputs, states_bw)
        return paddle.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
