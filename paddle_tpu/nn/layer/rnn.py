"""RNN layers (reference: python/paddle/nn/layer/rnn.py).

Time recursion runs under jax.lax.scan — the compiler-friendly control-flow
replacement for the reference's cudnn RNN kernels / per-step Python loops.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn import initializer as I
from paddle_tpu.tensor._ops_common import apply, ensure_tensor
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN", "LSTM", "GRU", "BiRNN", "RNNCellBase", "Decoder", "BeamSearchDecoder", "dynamic_decode"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32", init_value=0.0):
        batch = batch_ref.shape[0]
        return paddle.full([batch, self.hidden_size], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _cell(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply("simple_rnn_cell", _cell, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def _cell(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply("lstm_cell", _cell, inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        h = apply("gru_cell", _cell, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell into a scan over time (reference nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outputs = []
        x = inputs
        if not self.time_major:
            x = paddle.transpose(x, [1, 0] + list(range(2, x.ndim)))
        steps = range(x.shape[0] - 1, -1, -1) if self.is_reverse else range(x.shape[0])
        states = initial_states
        outs = [None] * x.shape[0]
        for t in steps:
            out, states = self.cell(x[t], states)
            outs[t] = out
        stacked = paddle.stack(outs, axis=0)
        if not self.time_major:
            stacked = paddle.transpose(stacked, [1, 0] + list(range(2, stacked.ndim)))
        return stacked, states


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrent network executed as a
    fused lax.scan per layer/direction — weights stacked so each time step is
    one batched matmul on the MXU."""

    mode = "RNN_TANH"

    def __init__(
        self,
        input_size,
        hidden_size,
        num_layers=1,
        direction="forward",
        time_major=False,
        dropout=0.0,
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        name=None,
    ):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        self.num_directions = num_dirs
        gate_mult = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}[self.mode]
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                suffix = f"_reverse" if d == 1 else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size, in_sz], weight_ih_attr, default_initializer=init),
                )
                self.add_parameter(
                    f"weight_hh_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size, hidden_size], weight_hh_attr, default_initializer=init),
                )
                self.add_parameter(
                    f"bias_ih_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init),
                )
                self.add_parameter(
                    f"bias_hh_l{layer}{suffix}",
                    self.create_parameter([gate_mult * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init),
                )

    def _step_fn(self):
        mode = self.mode

        def step(carry, xt, wi, wh, bi, bh):
            if mode == "LSTM":
                h, c = carry
                gates = xt @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return (h_new, c_new), h_new
            if mode == "GRU":
                h = carry
                gi = xt @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                return (1 - z) * c + z * h, (1 - z) * c + z * h
            h = carry
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
            h_new = act(xt @ wi.T + bi + h @ wh.T + bh)
            return h_new, h_new

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.mode == "LSTM"
        num_dirs = self.num_directions
        step = self._step_fn()

        params = []
        for layer in range(self.num_layers):
            for d in range(num_dirs):
                suffix = "_reverse" if d == 1 else ""
                params.append(
                    (
                        getattr(self, f"weight_ih_l{layer}{suffix}"),
                        getattr(self, f"weight_hh_l{layer}{suffix}"),
                        getattr(self, f"bias_ih_l{layer}{suffix}"),
                        getattr(self, f"bias_hh_l{layer}{suffix}"),
                    )
                )

        time_major = self.time_major
        num_layers = self.num_layers
        hidden = self.hidden_size
        mode = self.mode

        def _run(x, *flat_params):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
            B = x.shape[1]
            hs, cs = [], []
            inp = x
            idx = 0
            for layer in range(num_layers):
                outs_dir = []
                for d in range(num_dirs):
                    wi, wh, bi, bh = flat_params[idx * 4 : idx * 4 + 4]
                    idx += 1
                    h0 = jnp.zeros((B, hidden), x.dtype)
                    carry0 = (h0, jnp.zeros((B, hidden), x.dtype)) if is_lstm else h0
                    seq = jnp.flip(inp, 0) if d == 1 else inp

                    def scan_step(carry, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                        return step(carry, xt, wi, wh, bi, bh)

                    carry_f, out = jax.lax.scan(scan_step, carry0, seq)
                    if d == 1:
                        out = jnp.flip(out, 0)
                    outs_dir.append(out)
                    if is_lstm:
                        hs.append(carry_f[0])
                        cs.append(carry_f[1])
                    else:
                        hs.append(carry_f)
                inp = jnp.concatenate(outs_dir, axis=-1) if num_dirs == 2 else outs_dir[0]
            out = inp if time_major else jnp.swapaxes(inp, 0, 1)
            h_stack = jnp.stack(hs, axis=0)
            if is_lstm:
                c_stack = jnp.stack(cs, axis=0)
                return out, h_stack, c_stack
            return out, h_stack

        flat = [p for group in params for p in group]
        result = apply("rnn", _run, ensure_tensor(inputs), *flat)
        if is_lstm:
            out, h, c = result
            return out, (h, c)
        out, h = result
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh", **kw):
        self.mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction, time_major, dropout, **kw)


class LSTM(_RNNBase):
    mode = "LSTM"


class GRU(_RNNBase):
    mode = "GRU"


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, st_fw = self.fw(inputs, states_fw)
        out_bw, st_bw = self.bw(inputs, states_bw)
        return paddle.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


# --------------------------------------------------------------- decoding
class Decoder:
    """Abstract decode-step interface (reference: python/paddle/nn/decode.py
    Decoder): initialize() / step() / finalize()."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoder over an RNN cell (reference:
    python/paddle/nn/decode.py:BeamSearchDecoder).

    Host-driven eager loop (the schedule is data-dependent); each step's
    tensor math is jnp and the per-step cell call hits the jit cache, the
    same execution shape as the reference's per-step kernel launches.
    """

    def __init__(self, cell, start_token, end_token, beam_size, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token, self.end_token = int(start_token), int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] by repeating each row."""
        x = ensure_tensor(x)
        v = x._value
        v = jnp.repeat(v[:, None], beam_size, axis=1).reshape(-1, *v.shape[1:])
        return Tensor(v)

    def _merge(self, v):
        return v.reshape(-1, *v.shape[2:])  # [B, K, ...] -> [B*K, ...]

    def _split(self, v):
        return v.reshape(self.batch_size, self.beam_size, *v.shape[1:])

    @staticmethod
    def _tree_map_tensors(fn, tree):
        # Tensor is itself a registered pytree; map over whole Tensors, not
        # their leaves, or the reconstruction nests Tensor inside Tensor
        return jax.tree_util.tree_map(fn, tree, is_leaf=lambda x: isinstance(x, Tensor))

    def initialize(self, inits):
        sample = jax.tree_util.tree_leaves(inits)[0]
        self.batch_size = int(sample.shape[0])
        B, K = self.batch_size, self.beam_size
        states = self._tree_map_tensors(
            lambda t: Tensor(self._merge(jnp.repeat((t._value if isinstance(t, Tensor) else jnp.asarray(t))[:, None], K, axis=1))),
            inits,
        )
        ids = jnp.full((B, K), self.start_token, jnp.int32)
        # first beam active, others -inf so step 1 expands only beam 0
        log_probs = jnp.tile(jnp.array([0.0] + [-1e9] * (K - 1), jnp.float32), (B, 1))
        finished = jnp.zeros((B, K), bool)
        init_inputs = self._embed(ids)
        return init_inputs, (states, log_probs, finished), Tensor(finished)

    def _embed(self, ids):
        t = Tensor(self._merge(ids) if ids.ndim == 2 else ids)
        if self.embedding_fn is not None:
            return self.embedding_fn(t)
        return t

    def step(self, time, inputs, states_tuple, **kwargs):
        cell_states, log_probs, finished = states_tuple
        B, K = self.batch_size, self.beam_size
        out, next_states = self.cell(inputs, cell_states, **kwargs)
        logits = self.output_fn(out) if self.output_fn is not None else out
        lv = logits._value.astype(jnp.float32)
        V = lv.shape[-1]
        step_lp = jax.nn.log_softmax(lv, axis=-1).reshape(B, K, V)
        # finished beams only extend with end_token at prob 0
        fin_mask = jnp.full((V,), -1e9, jnp.float32).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], fin_mask[None, None, :], step_lp)
        total = log_probs[..., None] + step_lp  # [B, K, V]
        top_lp, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parent = (top_idx // V).astype(jnp.int32)  # [B, K]
        token = (top_idx % V).astype(jnp.int32)
        new_finished = jnp.take_along_axis(finished, parent, axis=1) | (token == self.end_token)
        # reorder cell states by parent beam
        flat_parent = (jnp.arange(B, dtype=jnp.int32)[:, None] * K + parent).reshape(-1)
        next_states = self._tree_map_tensors(
            lambda t: Tensor(jnp.take((t._value if isinstance(t, Tensor) else jnp.asarray(t)), flat_parent, axis=0)),
            next_states,
        )
        outputs = {
            "scores": Tensor(top_lp),
            "predicted_ids": Tensor(token),
            "parent_ids": Tensor(parent),
        }
        next_inputs = self._embed(token)
        return outputs, (next_states, top_lp, new_finished), next_inputs, Tensor(new_finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        import paddle_tpu.nn.functional as F

        ids = paddle.stack(outputs["predicted_ids"], axis=0)  # [T, B, K]
        parents = paddle.stack(outputs["parent_ids"], axis=0)
        return F.gather_tree(ids, parents), final_states


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False, impute_finished=False, is_test=False, return_length=False, **kwargs):
    """Run a Decoder until all sequences finish or max_step_num (reference:
    python/paddle/nn/decode.py dynamic_decode)."""
    import numpy as np

    inputs, states, finished = decoder.initialize(inits)
    collected = {"scores": [], "predicted_ids": [], "parent_ids": []}
    lengths = None
    step = 0
    # reference loops until all beams finish when max_step_num is None; keep
    # a high safety cap against non-terminating decoders and warn if hit.
    limit = int(max_step_num) if max_step_num is not None else 10_000
    while step < limit:
        outputs, states, inputs, finished = decoder.step(step, inputs, states, **kwargs)
        for k in collected:
            collected[k].append(outputs[k])
        fin = np.asarray(finished._value)
        if lengths is None:
            lengths = np.full(fin.shape, limit, np.int64)
        newly = (fin) & (lengths == limit)
        lengths[newly] = step + 1
        step += 1
        if fin.all():
            break
    else:
        if max_step_num is None:
            import warnings

            warnings.warn(
                f"dynamic_decode stopped at the {limit}-step safety cap with "
                "unfinished sequences; pass max_step_num to bound decoding "
                "explicitly",
                RuntimeWarning,
            )
    seqs, final_states = decoder.finalize(collected, states, lengths)
    if not output_time_major:
        # reference _transpose_batch_time: [T, B, K] -> [B, T, K]
        seqs = paddle.transpose(seqs, [1, 0, 2]) if seqs.ndim == 3 else seqs
    if return_length:
        return seqs, final_states, Tensor(jnp.asarray(np.minimum(lengths, step)))
    return seqs, final_states
