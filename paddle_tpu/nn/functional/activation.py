"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All map to jax.nn / jnp primitives — XLA fuses them into surrounding matmuls,
which is the TPU replacement for the reference's fused activation kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor, unary

relu = unary("relu", jax.nn.relu)
relu6 = unary("relu6", jax.nn.relu6)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
tanh = unary("tanh", jnp.tanh)
silu = unary("silu", jax.nn.silu)
swish = silu
mish = unary("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)))
hardswish = unary("hardswish", jax.nn.hard_swish)
hardsigmoid = unary("hardsigmoid", lambda v: jnp.clip(v / 6.0 + 0.5, 0.0, 1.0))
tanhshrink = unary("tanhshrink", lambda v: v - jnp.tanh(v))
softsign = unary("softsign", jax.nn.soft_sign)
log_sigmoid = unary("log_sigmoid", jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    x = ensure_tensor(x)
    # approximate rides kwargs (static, recorded on the Operator) so the
    # Pallas matmul-epilogue fusion pattern can read which gelu this is
    return apply(
        "gelu",
        lambda v, approximate=False: jax.nn.gelu(v, approximate=approximate),
        x, approximate=bool(approximate))


def leaky_relu(x, negative_slope=0.01, name=None):
    x = ensure_tensor(x)
    return apply("leaky_relu", lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return apply("elu", lambda v: jax.nn.elu(v, alpha), x)


def celu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return apply("celu", lambda v: jax.nn.celu(v, alpha), x)


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    x = ensure_tensor(x)
    return apply("selu", lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def _prelu(v, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(v > 0, v, wb * v)

    return apply("prelu", _prelu, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    x = ensure_tensor(x)
    if training:
        from paddle_tpu._core import random as rng

        def _rrelu(v):
            a = jax.random.uniform(rng.next_key(), v.shape, jnp.float32, lower, upper).astype(v.dtype)
            return jnp.where(v >= 0, v, a * v)

        return apply("rrelu", _rrelu, x)
    mid = (lower + upper) / 2.0
    return apply("rrelu", lambda v: jnp.where(v >= 0, v, mid * v), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    x = ensure_tensor(x)
    return apply("hardtanh", lambda v: jnp.clip(v, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return apply(
        "hardshrink", lambda v: jnp.where(jnp.abs(v) > threshold, v, jnp.zeros((), v.dtype)), x
    )


def softshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return apply(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, jnp.zeros((), v.dtype))),
        x,
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = ensure_tensor(x)
    return apply(
        "softplus",
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta),
        x,
    )


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    from paddle_tpu._core.dtype import to_jax_dtype

    dt = to_jax_dtype(dtype)

    def _sm(v, axis=int(axis)):
        if dt is not None:
            v = v.astype(dt)
        return jax.nn.softmax(v, axis=axis)

    # axis rides as a static kwarg so captured Operators expose it to
    # pattern matchers (static/rewrite.py checks it before fusing)
    return apply("softmax", _sm, x, axis=int(axis))


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    from paddle_tpu._core.dtype import to_jax_dtype

    dt = to_jax_dtype(dtype)

    def _lsm(v):
        if dt is not None:
            v = v.astype(dt)
        return jax.nn.log_softmax(v, axis=int(axis))

    return apply("log_softmax", _lsm, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = ensure_tensor(x)
    from paddle_tpu._core import random as rng

    def _gs(v):
        g = jax.random.gumbel(rng.next_key(), v.shape).astype(v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, jnp.ones((), y.dtype), axis=axis, inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return apply("gumbel_softmax", _gs, x)


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def _mo(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = list(v.shape[:ax]) + [c // groups, groups] + list(v.shape[ax + 1 :])
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return apply("maxout", _mo, x)


def glu(x, axis=-1, name=None):
    x = ensure_tensor(x)
    return apply("glu", lambda v: jax.nn.glu(v, axis=axis), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    x = ensure_tensor(x)
    return apply(
        "thresholded_relu", lambda v: jnp.where(v > threshold, v, jnp.asarray(value, v.dtype)), x
    )


# in-place activation tier (reference: `*_` exports of nn.functional)
def _act_inplace(base):
    def fn(x, *args, **kwargs):
        from paddle_tpu.tensor._ops_common import inplace_from

        return inplace_from(x, base, *args, **kwargs)

    fn.__name__ = base.__name__ + "_"
    fn.__doc__ = f"In-place variant of {base.__name__} (rebinds the wrapper; XLA donation makes the compiled form truly in-place)."
    return fn


relu_ = _act_inplace(relu)
elu_ = _act_inplace(elu)
leaky_relu_ = _act_inplace(leaky_relu)
hardtanh_ = _act_inplace(hardtanh)
softmax_ = _act_inplace(softmax)
tanh_ = _act_inplace(tanh)
thresholded_relu_ = _act_inplace(thresholded_relu)
