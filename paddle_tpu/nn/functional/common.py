"""Common functionals: linear, dropout, embedding, normalize, similarity,
interpolate, pad, unfold (reference: python/paddle/nn/functional/common.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core import random as rng
from paddle_tpu.tensor._ops_common import Tensor, apply, ensure_tensor


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W is [in, out] (paddle convention) — straight MXU matmul."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        return apply("linear", lambda v, w, b: jnp.matmul(v, w) + b, x, weight, bias)
    return apply("linear", lambda v, w: jnp.matmul(v, w), x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply("dropout_infer", lambda v: v * (1.0 - p), x)
        return x
    key = rng.next_key()

    def _drop(v):
        shape = list(v.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [a % v.ndim for a in axes] else 1 for i, s in enumerate(v.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype))
        return jnp.where(keep, v, jnp.zeros((), v.dtype))

    return apply("dropout", _drop, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axis = 1 if data_format == "NCHW" else 3
    return dropout(x, p, axis=[0, ch_axis], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axis = 1 if data_format == "NCDHW" else 4
    return dropout(x, p, axis=[0, ch_axis], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    key = rng.next_key()

    def _ad(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 - p + p * alpha_p**2) ** -0.5
        b = -a * p * alpha_p
        return a * jnp.where(keep, v, jnp.asarray(alpha_p, v.dtype)) + b

    return apply("alpha_dropout", _ad, x)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    return alpha_dropout(x, p, training)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def _emb(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out

    return apply("embedding", _emb, x, weight)


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return apply("one_hot", lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def _norm(v):
        n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)

    return apply("normalize", _norm, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def _cs(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply("cosine_similarity", _cs, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def _bl(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    if bias is not None:
        return apply("bilinear", _bl, x1, x2, weight, ensure_tensor(bias))
    return apply("bilinear", _bl, x1, x2, weight)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from paddle_tpu.tensor.manipulation import pad as _pad

    return _pad(x, pad, mode, value, data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference unfold op) — NCHW in, [N, C*kh*kw, L] out."""
    x = ensure_tensor(x)
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def _unfold(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        patches = jax.lax.conv_general_dilated_patches(
            v,
            filter_shape=ks,
            window_strides=st,
            padding="VALID",
            rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        # patches: [N, C*kh*kw, out_h, out_w]
        return patches.reshape(n, patches.shape[1], -1)

    return apply("unfold", _unfold, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = ensure_tensor(x)
    os = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def _fold(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os[0] + pd[0] + pd[2], os[1] + pd[1] + pd[3]
        out_h = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        out_w = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], out_h, out_w)
        result = jnp.zeros((n, c, ph, pw), v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hi = i * dl[0]
                wj = j * dl[1]
                result = result.at[
                    :, :, hi : hi + out_h * st[0] : st[0], wj : wj + out_w * st[1] : st[1]
                ].add(v[:, :, i, j])
        return result[:, :, pd[0] : ph - pd[2], pd[1] : pw - pd[3]]

    return apply("fold", _fold, x)


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    x = ensure_tensor(x)
    nd = x.ndim
    channel_last = data_format[-1] == "C"
    spatial = nd - 2

    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy()]
        out_size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * spatial)]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial
        in_sp = x.shape[2:] if not channel_last else x.shape[1:-1]
        out_size = [int(s * f) for s, f in zip(in_sp, sf)]

    jmode = {
        "nearest": "nearest",
        "bilinear": "linear",
        "trilinear": "linear",
        "linear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode.lower()]

    def _interp(v):
        if channel_last:
            full = [v.shape[0]] + out_size + [v.shape[-1]]
        else:
            full = [v.shape[0], v.shape[1]] + out_size
        if jmode == "nearest":
            return jax.image.resize(v, full, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate with explicit gather.
            return _resize_align_corners(v, full, jmode, channel_last)
        return jax.image.resize(v, full, method=jmode)

    return apply("interpolate", _interp, x)


def _resize_align_corners(v, full, method, channel_last):
    sp_axes = list(range(1, v.ndim - 1)) if channel_last else list(range(2, v.ndim))
    out = v
    for ax_i, ax in enumerate(sp_axes):
        in_n = out.shape[ax]
        out_n = full[ax]
        if in_n == out_n:
            continue
        if out_n == 1:
            idx = jnp.zeros((1,), jnp.float32)
        else:
            idx = jnp.linspace(0.0, in_n - 1, out_n)
        lo = jnp.floor(idx).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, in_n - 1)
        frac = (idx - lo).astype(out.dtype)
        shape = [1] * out.ndim
        shape[ax] = out_n
        frac = frac.reshape(shape)
        lo_g = jnp.take(out, lo, axis=ax)
        hi_g = jnp.take(out, hi, axis=ax)
        out = lo_g * (1 - frac) + hi_g * frac
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = upscale_factor

    def _ps(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply("pixel_shuffle", _ps, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = downscale_factor

    def _pu(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
        return v.reshape(n, h // r, w // r, c * r * r)

    return apply("pixel_unshuffle", _pu, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def _cs(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            v = jnp.swapaxes(v, 1, 2)
            return v.reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        v = jnp.swapaxes(v, 3, 4)
        return v.reshape(n, h, w, c)

    return apply("channel_shuffle", _cs, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)

    def _ls(v, *rest):
        k = v.shape[-1]
        if rest:
            return (1 - epsilon) * v + epsilon * rest[0]
        return (1 - epsilon) * v + epsilon / k

    if prior_dist is not None:
        return apply("label_smooth", _ls, label, ensure_tensor(prior_dist))
    return apply("label_smooth", _ls, label)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[..., j] = j < x[...] (reference: paddle.nn.functional.sequence_mask,
    python/paddle/nn/functional/extension.py)."""
    from paddle_tpu._core.dtype import to_jax_dtype

    x = ensure_tensor(x)
    if maxlen is None:
        import numpy as np

        maxlen = int(np.asarray(jnp.max(x._value)))  # data-dependent: eager only
    m = int(maxlen)
    dt = to_jax_dtype(dtype)

    def _fn(v):
        j = jnp.arange(m, dtype=jnp.int32)
        return (j[None, :] < v.reshape(-1, 1).astype(jnp.int32)).reshape(*v.shape, m).astype(dt)

    return apply("sequence_mask", _fn, x)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """p-norm of (x - y) along the last axis (reference:
    python/paddle/nn/functional/distance.py)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    pf = float(p)

    def _fn(a, b):
        d = jnp.abs(a - b) + jnp.asarray(epsilon, a.dtype)
        if pf == float("inf"):
            out = jnp.max(d, axis=-1, keepdims=keepdim)
        elif pf == 0.0:
            out = jnp.sum((d != 0).astype(a.dtype), axis=-1, keepdims=keepdim)
        else:
            out = jnp.sum(d**pf, axis=-1, keepdims=keepdim) ** (1.0 / pf)
        return out

    return apply("pairwise_distance", _fn, x, y)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: paddle.nn.functional.gather_tree,
    paddle/phi/kernels/cpu/gather_tree_kernel.cc): walk parent pointers from
    the last step to recover full predicted sequences.
    ids/parents: [max_time, batch, beam]."""
    ids, parents = ensure_tensor(ids), ensure_tensor(parents)

    def _fn(idv, parv):
        T = idv.shape[0]
        beams = jnp.arange(idv.shape[2], dtype=parv.dtype)
        init_parent = jnp.broadcast_to(beams, idv.shape[1:])

        # walk from last step backwards gathering tokens along parent chain
        def scan_body(parent, t):
            tok = jnp.take_along_axis(idv[t], parent.astype(jnp.int32), axis=-1)
            new_parent = jnp.take_along_axis(parv[t], parent.astype(jnp.int32), axis=-1)
            return new_parent, tok

        ts = jnp.arange(T - 1, -1, -1)
        _, toks = jax.lax.scan(scan_body, init_parent, ts)
        return jnp.flip(toks, axis=0)

    return apply("gather_tree", _fn, ids, parents)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """TSM temporal shift (reference: paddle/phi/kernels/gpu/temporal_shift
    kernel): shift a slice of channels one step forward/backward in time."""
    x = ensure_tensor(x)

    def _fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        NT, C, H, W = v.shape
        N = NT // int(seg_num)
        v5 = v.reshape(N, int(seg_num), C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        back = jnp.concatenate([v5[:, 1:, :c1], jnp.zeros_like(v5[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v5[:, :1, c1:c2]), v5[:, :-1, c1:c2]], axis=1)
        keep = v5[:, :, c2:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply("temporal_shift", _fn, x)
