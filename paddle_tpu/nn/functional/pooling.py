"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py) —
lax.reduce_window is the XLA-native pooling primitive."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in (v if len(v) == n else list(v) * n)[:n])
    return tuple(int(v) for _ in range(n))


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _pool(x, kernel, stride, padding, nd, data_format, reducer, init, ceil_mode=False, count_include_pad=True, is_avg=False):
    x = ensure_tensor(x)
    ks = _tuple(kernel, nd)
    st = _tuple(stride if stride is not None else kernel, nd)
    channel_last = data_format[-1] == "C"
    if channel_last:
        dims = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
    else:
        dims = (1, 1) + ks
        strides = (1, 1) + st
    pd = _pads(padding, nd)
    if isinstance(pd, str):
        pad_full = pd
    else:
        pad_full = ([(0, 0)] + list(pd) + [(0, 0)]) if channel_last else ([(0, 0), (0, 0)] + list(pd))

    def _p(v):
        if is_avg:
            ones = jnp.ones_like(v)
            s = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pad_full)
            if count_include_pad and not isinstance(pad_full, str):
                denom = float(np.prod(ks))
                return s / denom
            c = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pad_full)
            return s / c
        return jax.lax.reduce_window(v, init, reducer, dims, strides, pad_full)

    return apply("pool", _p, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.max, -jnp.inf)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.max, -jnp.inf)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.max, -jnp.inf)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.add, 0.0, is_avg=True, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.add, 0.0, is_avg=True, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.add, 0.0, is_avg=True, count_include_pad=not exclusive)


def _adaptive_pool(x, output_size, nd, data_format, is_avg):
    x = ensure_tensor(x)
    os = _tuple(output_size, nd)
    channel_last = data_format[-1] == "C"

    def _ap(v):
        sp_axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
        out = v
        for ax, o in zip(sp_axes, os):
            n = out.shape[ax]
            # split into o regions with boundaries floor(i*n/o) .. ceil((i+1)*n/o)
            starts = [int(np.floor(i * n / o)) for i in range(o)]
            ends = [int(np.ceil((i + 1) * n / o)) for i in range(o)]
            pieces = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(s, e)
                seg = out[tuple(sl)]
                red = jnp.mean(seg, axis=ax, keepdims=True) if is_avg else jnp.max(seg, axis=ax, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply("adaptive_pool", _ap, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, True)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, True)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", False)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)

    def _lp(v):
        from paddle_tpu.nn.functional.pooling import _pads, _tuple  # self-import ok

        ks = _tuple(kernel_size, 1)
        st = _tuple(stride if stride is not None else kernel_size, 1)
        dims = (1, 1) + ks
        strides = (1, 1) + st
        pd = _pads(padding, 1)
        pad_full = [(0, 0), (0, 0)] + list(pd)
        s = jax.lax.reduce_window(jnp.abs(v) ** p, 0.0, jax.lax.add, dims, strides, pad_full)
        return s ** (1.0 / p)

    return apply("lp_pool1d", _lp, x)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)

    def _lp(v):
        ks = _tuple(kernel_size, 2)
        st = _tuple(stride if stride is not None else kernel_size, 2)
        dims = (1, 1) + ks
        strides = (1, 1) + st
        pd = _pads(padding, 2)
        pad_full = [(0, 0), (0, 0)] + list(pd)
        s = jax.lax.reduce_window(jnp.abs(v) ** p, 0.0, jax.lax.add, dims, strides, pad_full)
        return s ** (1.0 / p)

    return apply("lp_pool2d", _lp, x)
