"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py) —
lax.reduce_window is the XLA-native pooling primitive."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in (v if len(v) == n else list(v) * n)[:n])
    return tuple(int(v) for _ in range(n))


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _ceil_extra(n, k, s, lo, hi):
    """Extra right-padding making reduce_window emit the ceil-mode output
    size: out = ceil((n + lo + hi - k)/s) + 1 (reference pooling ceil
    semantics; lo/hi may differ under 2n-form padding)."""
    import math

    total = n + lo + hi
    out = math.ceil(max(total - k, 0) / s) + 1
    return max((out - 1) * s + k - total, 0)


def _pool(x, kernel, stride, padding, nd, data_format, reducer, init, ceil_mode=False, count_include_pad=True, is_avg=False):
    x = ensure_tensor(x)
    ks = _tuple(kernel, nd)
    st = _tuple(stride if stride is not None else kernel, nd)
    channel_last = data_format[-1] == "C"
    if channel_last:
        dims = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
    else:
        dims = (1, 1) + ks
        strides = (1, 1) + st
    pd = _pads(padding, nd)
    if isinstance(pd, str):
        pad_full = pd
    else:
        if ceil_mode:
            sp_shape = x.shape[1 : 1 + nd] if channel_last else x.shape[2 : 2 + nd]
            pd = [
                (lo, hi + _ceil_extra(int(n), k, s, lo, hi))
                for (lo, hi), n, k, s in zip(pd, sp_shape, ks, st)
            ]
        pad_full = ([(0, 0)] + list(pd) + [(0, 0)]) if channel_last else ([(0, 0), (0, 0)] + list(pd))

    def _p(v):
        if is_avg:
            ones = jnp.ones_like(v)
            s = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pad_full)
            if count_include_pad and not isinstance(pad_full, str):
                denom = float(np.prod(ks))
                return s / denom
            c = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pad_full)
            return s / c
        return jax.lax.reduce_window(v, init, reducer, dims, strides, pad_full)

    return apply("pool", _p, x)


def _max_pool_with_mask(x, kernel_size, stride, padding, nd, data_format, ceil_mode=False):
    """Max pool returning (out, mask): mask holds each max's flat index
    within its (N, C) spatial map — the layout max_unpool consumes
    (reference: paddle/phi/kernels/funcs/pooling.h MaxPool2dWithIndex)."""
    x = ensure_tensor(x)
    ks = _tuple(kernel_size, nd)
    st = _tuple(stride if stride is not None else kernel_size, nd)
    pd = _pads(padding, nd)
    if isinstance(pd, str):
        raise ValueError("return_mask does not support string padding")
    if data_format[-1] == "C":
        raise ValueError("return_mask supports channel-first layouts only")
    if ceil_mode:
        pd = [
            (lo, hi + _ceil_extra(int(n), k, s, lo, hi))
            for (lo, hi), n, k, s in zip(pd, x.shape[2 : 2 + nd], ks, st)
        ]

    def _fn(v):
        N, C = v.shape[0], v.shape[1]
        spatial = v.shape[2:]
        if int(np.prod(spatial)) > (1 << 24):
            # indices ride a float32 patch extraction; above 2^24 they lose
            # exactness and unpool would scatter to wrong positions
            raise ValueError(
                "return_mask supports spatial maps up to 2^24 elements per "
                f"channel; got {int(np.prod(spatial))}"
            )
        flat_idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(1, 1, *spatial)
        flat_idx = jnp.broadcast_to(flat_idx, v.shape)
        # pad values with -inf (never wins argmax) and indices with 0 BEFORE
        # patch extraction — conv patches would otherwise zero-pad values
        pad_cfg = [(0, 0), (0, 0)] + [(p[0], p[1]) for p in pd]
        vpad = jnp.pad(v, pad_cfg, constant_values=-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min)
        ipad = jnp.pad(flat_idx, pad_cfg, constant_values=0)
        # patches: [N, C*prod(ks), *out_spatial]
        patches = jax.lax.conv_general_dilated_patches(
            vpad, filter_shape=ks, window_strides=st, padding="VALID"
        )
        ipatches = jax.lax.conv_general_dilated_patches(
            ipad.astype(jnp.float32), filter_shape=ks, window_strides=st, padding="VALID"
        )
        out_sp = patches.shape[2:]
        K = int(np.prod(ks))
        pv = patches.reshape(N, C, K, *out_sp)
        piv = ipatches.reshape(N, C, K, *out_sp)
        arg = jnp.argmax(pv, axis=2)
        out = jnp.max(pv, axis=2)
        mask = jnp.take_along_axis(piv, arg[:, :, None], axis=2)[:, :, 0].astype(jnp.int32)
        return out, mask

    return apply("max_pool_with_mask", _fn, x, n_outputs=2)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1, data_format, ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.max, -jnp.inf, ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2, data_format, ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.max, -jnp.inf, ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3, data_format, ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.max, -jnp.inf, ceil_mode=ceil_mode)


def _max_unpool(x, indices, nd, kernel_size, stride=None, padding=0, output_size=None, data_format="NCHW"):
    """Scatter pooled values back to their argmax positions (reference:
    paddle/phi/kernels/cpu/unpool_kernel.cc)."""
    x, indices = ensure_tensor(x), ensure_tensor(indices)
    ks = _tuple(kernel_size, nd)
    st = _tuple(stride if stride is not None else kernel_size, nd)
    pd = _pads(padding, nd)
    in_sp = x.shape[2:]
    if output_size is None:
        out_sp = tuple(
            (in_sp[i] - 1) * st[i] - 2 * pd[i][0] + ks[i] for i in range(nd)
        )
    else:
        out_sp = tuple(int(s) for s in (output_size[-nd:] if len(output_size) > nd else output_size))

    def _fn(v, idx):
        N, C = v.shape[0], v.shape[1]
        L = int(np.prod(v.shape[2:]))
        M = int(np.prod(out_sp))
        vf = v.reshape(N * C, L)
        if_ = idx.reshape(N * C, L).astype(jnp.int32)
        out = jnp.zeros((N * C, M), v.dtype)
        out = out.at[jnp.arange(N * C, dtype=jnp.int32)[:, None], if_].set(vf)
        return out.reshape(N, C, *out_sp)

    return apply("max_unpool", _fn, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding, output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding, output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0, data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding, output_size, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, jax.lax.add, 0.0, ceil_mode=ceil_mode, is_avg=True, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.add, 0.0, ceil_mode=ceil_mode, is_avg=True, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.add, 0.0, ceil_mode=ceil_mode, is_avg=True, count_include_pad=not exclusive)


def _adaptive_pool(x, output_size, nd, data_format, is_avg):
    x = ensure_tensor(x)
    os = _tuple(output_size, nd)
    channel_last = data_format[-1] == "C"

    def _ap(v):
        sp_axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
        out = v
        for ax, o in zip(sp_axes, os):
            n = out.shape[ax]
            # split into o regions with boundaries floor(i*n/o) .. ceil((i+1)*n/o)
            starts = [int(np.floor(i * n / o)) for i in range(o)]
            ends = [int(np.ceil((i + 1) * n / o)) for i in range(o)]
            pieces = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(s, e)
                seg = out[tuple(sl)]
                red = jnp.mean(seg, axis=ax, keepdims=True) if is_avg else jnp.max(seg, axis=ax, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply("adaptive_pool", _ap, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, True)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, True)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", False)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)

    def _lp(v):
        from paddle_tpu.nn.functional.pooling import _pads, _tuple  # self-import ok

        ks = _tuple(kernel_size, 1)
        st = _tuple(stride if stride is not None else kernel_size, 1)
        dims = (1, 1) + ks
        strides = (1, 1) + st
        pd = _pads(padding, 1)
        pad_full = [(0, 0), (0, 0)] + list(pd)
        s = jax.lax.reduce_window(jnp.abs(v) ** p, 0.0, jax.lax.add, dims, strides, pad_full)
        return s ** (1.0 / p)

    return apply("lp_pool1d", _lp, x)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)

    def _lp(v):
        ks = _tuple(kernel_size, 2)
        st = _tuple(stride if stride is not None else kernel_size, 2)
        dims = (1, 1) + ks
        strides = (1, 1) + st
        pd = _pads(padding, 2)
        pad_full = [(0, 0), (0, 0)] + list(pd)
        s = jax.lax.reduce_window(jnp.abs(v) ** p, 0.0, jax.lax.add, dims, strides, pad_full)
        return s ** (1.0 / p)

    return apply("lp_pool2d", _lp, x)
