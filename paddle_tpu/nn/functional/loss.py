"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import Tensor, apply, ensure_tensor


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _ce(logits, lbl, *rest):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            lbl_idx = lbl.astype(jnp.int32)
            if lbl_idx.ndim == logits.ndim:
                lbl_idx = jnp.squeeze(lbl_idx, axis=axis)
            if label_smoothing > 0:
                oh = jax.nn.one_hot(lbl_idx, n_classes, dtype=logp.dtype, axis=axis)
                soft = oh * (1 - label_smoothing) + label_smoothing / n_classes
                loss = -jnp.sum(soft * logp, axis=axis)
            else:
                picked = jnp.take_along_axis(logp, jnp.expand_dims(lbl_idx, axis), axis=axis)
                loss = -jnp.squeeze(picked, axis=axis)
            mask = lbl_idx != ignore_index
            loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
            if rest:
                w = rest[0]
                wsel = jnp.take(w, jnp.clip(lbl_idx, 0, n_classes - 1))
                loss = loss * jnp.where(mask, wsel, 0.0)
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(mask, wsel, 0.0)), 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("cross_entropy", _ce, input, label, *extra)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    logits, label = ensure_tensor(logits), ensure_tensor(label)

    def _swce(lg, lb):
        logp = jax.nn.log_softmax(lg, axis=axis)
        if soft_label:
            loss = -jnp.sum(lb * logp, axis=axis, keepdims=True)
        else:
            idx = lb.astype(jnp.int32)
            squeeze = idx.ndim == lg.ndim
            if squeeze:
                idx = jnp.squeeze(idx, axis=axis)
            picked = jnp.take_along_axis(logp, jnp.expand_dims(idx, axis), axis=axis)
            loss = -picked
            mask = jnp.expand_dims(idx, axis) != ignore_index
            loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
        if return_softmax:
            return loss, jax.nn.softmax(lg, axis=axis)
        return loss

    return apply("softmax_with_cross_entropy", _swce, logits, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _nll(logp, lbl, *rest):
        idx = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(idx, 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        mask = idx != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if rest:
            w = jnp.take(rest[0], jnp.clip(idx, 0, logp.shape[1] - 1))
            w = jnp.where(mask, w, 0.0)
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("nll_loss", _nll, input, label, *extra)


def mse_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _sl1(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply("smooth_l1_loss", _sl1, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _huber(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply("huber_loss", _huber, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _bce(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("bce", _bce, input, label, *extra)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)

    def _bcel(z, y, *rest):
        it = iter(rest)
        w = next(it) if weight is not None else None
        pw = next(it) if pos_weight is not None else None
        max_val = jnp.maximum(-z, 0.0)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val)) + max_val)
        else:
            loss = (1 - y) * z + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    extra = [ensure_tensor(t) for t in (weight, pos_weight) if t is not None]
    return apply("bce_with_logits", _bcel, logit, label, *extra)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _kl(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply("kl_div", _kl, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    input, other, label = ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)
    return apply(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0.0), reduction),
        input,
        other,
        label,
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(
        "hinge_embedding_loss",
        lambda x, y: _reduce(jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0)), reduction),
        input,
        label,
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    input1, input2, label = ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)

    def _cel(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return apply("cosine_embedding_loss", _cel, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    input, positive, negative = ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative)

    def _tml(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1.0 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1.0 / p)
        if swap:
            dsn = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1.0 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply("triplet_margin_loss", _tml, input, positive, negative)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the classic alpha recursion in log space, vectorized with scan
    (reference: warpctc kernel paddle/phi/kernels/gpu/warpctc_kernel.cu)."""
    log_probs, labels = ensure_tensor(log_probs), ensure_tensor(labels)
    input_lengths, label_lengths = ensure_tensor(input_lengths), ensure_tensor(label_lengths)

    def _ctc(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] log-softmax already? paddle expects raw logits? docs: log_probs
        T, B, C = lp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended labels with blanks
        ext = jnp.full((B, S), blank, dtype=lbl.dtype)
        ext = ext.at[:, 1::2].set(lbl)
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        def get_probs(t_lp):
            return jnp.take_along_axis(t_lp[:, :], ext, axis=1)  # [B, S]

        alpha0 = jnp.full((B, S), neg_inf, lp.dtype)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lbl = jnp.take_along_axis(lp[0], jnp.clip(ext[:, 1:2], 0, C - 1), axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lbl_len > 0, first_lbl, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, t_lp):
            p = jnp.take_along_axis(t_lp, jnp.clip(ext, 0, C - 1), axis=1)
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf, lp.dtype), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf, lp.dtype), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
            m_safe = jnp.where(m == neg_inf, 0.0, m)
            summed = (
                jnp.exp(a_prev - m_safe)
                + jnp.exp(a_shift1 - m_safe)
                + jnp.where(a_shift2 == neg_inf, 0.0, jnp.exp(a_shift2 - m_safe))
            )
            new_alpha = jnp.where(m == neg_inf, neg_inf, m_safe + jnp.log(summed)) + p
            return new_alpha, new_alpha

        alpha_T, alphas = jax.lax.scan(step, alpha0, lp[1:])
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]
        # gather alpha at t = in_len-1, s in {2*lbl_len, 2*lbl_len-1}
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        aT = jnp.take_along_axis(all_alphas, t_idx[None, :, None], axis=0)[0]  # [B,S]
        sl = jnp.clip(2 * lbl_len, 0, S - 1)
        sl1 = jnp.clip(2 * lbl_len - 1, 0, S - 1)
        a1 = jnp.take_along_axis(aT, sl[:, None], axis=1)[:, 0]
        a2 = jnp.take_along_axis(aT, sl1[:, None], axis=1)[:, 0]
        m = jnp.maximum(a1, a2)
        m_safe = jnp.where(m == neg_inf, 0.0, m)
        ll = m_safe + jnp.log(jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply("ctc_loss", _ctc, log_probs, labels, input_lengths, label_lengths)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)

    def _focal(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            alpha_t = alpha * y + (1 - alpha) * (1 - y)
            loss = alpha_t * loss
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)

    extra = [ensure_tensor(normalizer)] if normalizer is not None else []
    return apply("sigmoid_focal_loss", _focal, logit, label, *extra)


def square_error_cost(input, label):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input,
        label,
    )


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _pnll(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(jnp.maximum(y, 1.0)) - y + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(y, 1.0))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply("poisson_nll_loss", _pnll, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean", name=None):
    input, label, variance = ensure_tensor(input), ensure_tensor(label), ensure_tensor(variance)

    def _gnll(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)

    return apply("gaussian_nll_loss", _gnll, input, label, variance)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _ml(z, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if rest:
            loss = loss * rest[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("multi_label_soft_margin_loss", _ml, input, label, *extra)


def soft_margin_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(
        "soft_margin_loss",
        lambda z, y: _reduce(jnp.log1p(jnp.exp(-y * z)), reduction),
        input,
        label,
    )


def dice_loss(input, label, epsilon=1e-5, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _dice(p, y):
        y_oh = jax.nn.one_hot(jnp.squeeze(y, -1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y_oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y_oh, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", _dice, input, label)
