"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import Tensor, apply, ensure_tensor


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _ce(logits, lbl, *rest):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            lbl_idx = lbl.astype(jnp.int32)
            if lbl_idx.ndim == logits.ndim:
                lbl_idx = jnp.squeeze(lbl_idx, axis=axis)
            if label_smoothing > 0:
                oh = jax.nn.one_hot(lbl_idx, n_classes, dtype=logp.dtype, axis=axis)
                soft = oh * (1 - label_smoothing) + label_smoothing / n_classes
                loss = -jnp.sum(soft * logp, axis=axis)
            else:
                picked = jnp.take_along_axis(logp, jnp.expand_dims(lbl_idx, axis), axis=axis)
                loss = -jnp.squeeze(picked, axis=axis)
            mask = lbl_idx != ignore_index
            loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
            if rest:
                w = rest[0]
                wsel = jnp.take(w, jnp.clip(lbl_idx, 0, n_classes - 1))
                loss = loss * jnp.where(mask, wsel, 0.0)
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(mask, wsel, 0.0)), 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("cross_entropy", _ce, input, label, *extra)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    logits, label = ensure_tensor(logits), ensure_tensor(label)

    def _swce(lg, lb):
        logp = jax.nn.log_softmax(lg, axis=axis)
        if soft_label:
            loss = -jnp.sum(lb * logp, axis=axis, keepdims=True)
        else:
            idx = lb.astype(jnp.int32)
            squeeze = idx.ndim == lg.ndim
            if squeeze:
                idx = jnp.squeeze(idx, axis=axis)
            picked = jnp.take_along_axis(logp, jnp.expand_dims(idx, axis), axis=axis)
            loss = -picked
            mask = jnp.expand_dims(idx, axis) != ignore_index
            loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
        if return_softmax:
            return loss, jax.nn.softmax(lg, axis=axis)
        return loss

    return apply("softmax_with_cross_entropy", _swce, logits, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _nll(logp, lbl, *rest):
        idx = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(idx, 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        mask = idx != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if rest:
            w = jnp.take(rest[0], jnp.clip(idx, 0, logp.shape[1] - 1))
            w = jnp.where(mask, w, 0.0)
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("nll_loss", _nll, input, label, *extra)


def mse_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _sl1(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply("smooth_l1_loss", _sl1, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _huber(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply("huber_loss", _huber, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _bce(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("bce", _bce, input, label, *extra)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)

    def _bcel(z, y, *rest):
        it = iter(rest)
        w = next(it) if weight is not None else None
        pw = next(it) if pos_weight is not None else None
        max_val = jnp.maximum(-z, 0.0)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val)) + max_val)
        else:
            loss = (1 - y) * z + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    extra = [ensure_tensor(t) for t in (weight, pos_weight) if t is not None]
    return apply("bce_with_logits", _bcel, logit, label, *extra)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _kl(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply("kl_div", _kl, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    input, other, label = ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)
    return apply(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0.0), reduction),
        input,
        other,
        label,
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(
        "hinge_embedding_loss",
        lambda x, y: _reduce(jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0)), reduction),
        input,
        label,
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    input1, input2, label = ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)

    def _cel(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return apply("cosine_embedding_loss", _cel, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    input, positive, negative = ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative)

    def _tml(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1.0 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1.0 / p)
        if swap:
            dsn = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1.0 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply("triplet_margin_loss", _tml, input, positive, negative)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the classic alpha recursion in log space, vectorized with scan
    (reference: warpctc kernel paddle/phi/kernels/gpu/warpctc_kernel.cu)."""
    log_probs, labels = ensure_tensor(log_probs), ensure_tensor(labels)
    input_lengths, label_lengths = ensure_tensor(input_lengths), ensure_tensor(label_lengths)

    def _ctc(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] log-softmax already? paddle expects raw logits? docs: log_probs
        T, B, C = lp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended labels with blanks
        ext = jnp.full((B, S), blank, dtype=lbl.dtype)
        ext = ext.at[:, 1::2].set(lbl)
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        def get_probs(t_lp):
            return jnp.take_along_axis(t_lp[:, :], ext, axis=1)  # [B, S]

        alpha0 = jnp.full((B, S), neg_inf, lp.dtype)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lbl = jnp.take_along_axis(lp[0], jnp.clip(ext[:, 1:2], 0, C - 1), axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lbl_len > 0, first_lbl, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, t_lp):
            # logaddexp keeps every operand FINITE (-1e30 sentinels), so
            # the backward is NaN-free — the previous max-shift form
            # produced inf*0 gradients through its log(0) dead branches
            p = jnp.take_along_axis(t_lp, jnp.clip(ext, 0, C - 1), axis=1)
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf, lp.dtype), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf, lp.dtype), alpha[:, :-2]], axis=1)
            acc = jnp.logaddexp(alpha, a_shift1)
            acc = jnp.where(same_as_prev2, acc, jnp.logaddexp(acc, a_shift2))
            # clamp so dead paths cannot drift below the sentinel range
            new_alpha = jnp.maximum(acc + p, neg_inf)
            return new_alpha, new_alpha

        alpha_T, alphas = jax.lax.scan(step, alpha0, lp[1:])
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]
        # gather alpha at t = in_len-1, s in {2*lbl_len, 2*lbl_len-1}
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        aT = jnp.take_along_axis(all_alphas, t_idx[None, :, None], axis=0)[0]  # [B,S]
        sl = jnp.clip(2 * lbl_len, 0, S - 1)
        sl1 = jnp.clip(2 * lbl_len - 1, 0, S - 1)
        a1 = jnp.take_along_axis(aT, sl[:, None], axis=1)[:, 0]
        a2 = jnp.take_along_axis(aT, sl1[:, None], axis=1)[:, 0]
        # empty target: both indices clip to 0 — mask the duplicate or the
        # all-blank path is double-counted (exactly log 2 too likely)
        a2 = jnp.where(lbl_len > 0, a2, neg_inf)
        ll = jnp.logaddexp(a1, a2)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply("ctc_loss", _ctc, log_probs, labels, input_lengths, label_lengths)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)

    def _focal(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            alpha_t = alpha * y + (1 - alpha) * (1 - y)
            loss = alpha_t * loss
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)

    extra = [ensure_tensor(normalizer)] if normalizer is not None else []
    return apply("sigmoid_focal_loss", _focal, logit, label, *extra)


def square_error_cost(input, label):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input,
        label,
    )


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _pnll(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(jnp.maximum(y, 1.0)) - y + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(y, 1.0))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply("poisson_nll_loss", _pnll, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean", name=None):
    input, label, variance = ensure_tensor(input), ensure_tensor(label), ensure_tensor(variance)

    def _gnll(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)

    return apply("gaussian_nll_loss", _gnll, input, label, variance)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _ml(z, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if rest:
            loss = loss * rest[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("multi_label_soft_margin_loss", _ml, input, label, *extra)


def soft_margin_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(
        "soft_margin_loss",
        lambda z, y: _reduce(jnp.log1p(jnp.exp(-y * z)), reduction),
        input,
        label,
    )


def dice_loss(input, label, epsilon=1e-5, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def _dice(p, y):
        y_oh = jax.nn.one_hot(jnp.squeeze(y, -1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y_oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y_oh, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", _dice, input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None, reduction="mean", name=None):
    """Reference: python/paddle/nn/functional/loss.py multi_margin_loss —
    mean_j max(0, margin - x_y + x_j)^p over j != y."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    extras = [ensure_tensor(weight)] if weight is not None else []

    def _fn(x, y, *w):
        C = x.shape[1]
        xy = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.maximum(0.0, jnp.asarray(margin, x.dtype) - xy + x)
        if int(p) == 2:
            m = m * m
        if w:
            m = m * jnp.take(w[0], y.astype(jnp.int32))[:, None]
        onehot = jax.nn.one_hot(y.astype(jnp.int32), C, dtype=x.dtype)
        m = m * (1.0 - onehot)
        return _reduce(jnp.sum(m, axis=1) / C, reduction)

    return apply("multi_margin_loss", _fn, input, label, *extras)


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None, margin=1.0, swap=False, reduction="mean", name=None):
    """Reference: python/paddle/nn/functional/loss.py — triplet loss with a
    user distance callable (defaults to pairwise L2)."""
    from .common import pairwise_distance

    input, positive, negative = ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative)
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_pos = ensure_tensor(dist(input, positive))
    d_neg = ensure_tensor(dist(input, negative))
    if swap:
        d_neg2 = ensure_tensor(dist(positive, negative))
        from paddle_tpu.tensor.math import minimum

        d_neg = minimum(d_neg, d_neg2)

    def _fn(dp, dn):
        return _reduce(jnp.maximum(0.0, dp - dn + jnp.asarray(margin, dp.dtype)), reduction)

    return apply("triplet_margin_with_distance_loss", _fn, d_pos, d_neg)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Reference: python/paddle/nn/functional/loss.py npair_loss (N-pair
    paper, Sohn 2016): softmax CE over anchor@positive^T similarities with
    same-label targets + L2 on the embeddings."""
    anchor, positive, labels = ensure_tensor(anchor), ensure_tensor(positive), ensure_tensor(labels)

    def _fn(a, pos, y):
        yf = y.reshape(-1, 1).astype(jnp.float32)
        same = (yf == yf.T).astype(jnp.float32)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        sim = a.astype(jnp.float32) @ pos.astype(jnp.float32).T
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = jnp.asarray(l2_reg, jnp.float32) * (jnp.mean(jnp.sum(a * a, axis=1)) + jnp.mean(jnp.sum(pos * pos, axis=1))) / 2.0
        return (ce + reg).astype(a.dtype)

    return apply("npair_loss", _fn, anchor, positive, labels)


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (reference:
    python/paddle/nn/functional/loss.py hsigmoid_loss,
    paddle/phi/kernels/cpu/hsigmoid_loss_kernel.cc).

    Default tree: complete binary tree over num_classes leaves — inner node
    path/codes derive from the label's binary route, exactly the reference's
    default layout.  Custom trees come in via path_table/path_code.
    """
    input, label = ensure_tensor(input), ensure_tensor(label)
    weight = ensure_tensor(weight)
    extras = [weight] + ([ensure_tensor(bias)] if bias is not None else [])

    if path_table is None:
        # default complete-binary-tree: code length = ceil(log2(C)); node ids
        # follow the heap layout the reference uses (root = class C offset).
        C = int(num_classes)
        depth = max(1, int(np.ceil(np.log2(C))))

        def _route(y):
            # heap position of leaf y is (y + C - 1) in a 1-indexed heap of
            # inner nodes [0, C-2]; walk up collecting (parent, is_right)
            nodes, codes = [], []
            n = y + C - 1
            for _ in range(depth):
                parent = (n - 1) // 2
                codes.append(n % 2 == 0)  # right child has even heap index
                nodes.append(parent)
                n = parent
                if parent == 0:
                    break
            while len(nodes) < depth:
                nodes.append(-1)
                codes.append(False)
            return nodes[::-1], codes[::-1]

        tbl = np.full((C, depth), -1, np.int32)
        cde = np.zeros((C, depth), np.float32)
        for y in range(C):
            nn_, cc_ = _route(y)
            tbl[y, : len(nn_)] = nn_
            cde[y, : len(cc_)] = [1.0 if c else 0.0 for c in cc_]
        path_table_arr, path_code_arr = jnp.asarray(tbl), jnp.asarray(cde)
    else:
        path_table_arr = ensure_tensor(path_table)._value
        path_code_arr = ensure_tensor(path_code)._value.astype(jnp.float32)

    def _fn(x, y, wv, *b):
        # per-sample paths: [B, D]
        if path_table is not None:
            tb = path_table_arr
            cd = path_code_arr
        else:
            tb = jnp.take(path_table_arr, y.astype(jnp.int32), axis=0)
            cd = jnp.take(path_code_arr, y.astype(jnp.int32), axis=0)
        valid = (tb >= 0).astype(jnp.float32)
        tb_c = jnp.maximum(tb, 0).astype(jnp.int32)
        w = jnp.take(wv, tb_c, axis=0)  # [B, D, F]
        logit = jnp.einsum("bdf,bf->bd", w.astype(jnp.float32), x.astype(jnp.float32))
        if b:
            logit = logit + jnp.take(b[0].reshape(-1), tb_c).astype(jnp.float32)
        # BCE with code as target: -[c*log(sig) + (1-c)*log(1-sig)]
        loss = jnp.maximum(logit, 0.0) - logit * cd + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        return jnp.sum(loss * valid, axis=1, keepdims=True).astype(x.dtype)

    return apply("hsigmoid_loss", _fn, input, label, *extras)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0, fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (reference: python/paddle/nn/functional/loss.py
    rnnt_loss over warprnnt): forward algorithm on the (T, U) lattice with a
    lax.scan over time — log-space alpha recursion, jit-friendly.

    input: [B, T, U+1, D] log-probs or logits (normalized here), label [B, U].
    """
    input, label = ensure_tensor(input), ensure_tensor(label)
    input_lengths, label_lengths = ensure_tensor(input_lengths), ensure_tensor(label_lengths)

    def _fn(logits, y, tlen, ulen):
        B, T, U1, D = logits.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # emission probs: p(y_u | t, u) and blank probs p(blank | t, u)
        yb = jnp.pad(y.astype(jnp.int32), ((0, 0), (0, 1)))  # [B, U+1]
        p_emit = jnp.take_along_axis(logp, yb[:, None, :, None], axis=3)[..., 0]  # [B,T,U+1]
        if float(fastemit_lambda) > 0.0:
            # FastEmit (Yu et al. 2021): scale emission-arc GRADIENTS by
            # (1+lambda) without changing the loss value — value-preserving
            # gradient boost via stop_gradient.
            lam = jnp.float32(fastemit_lambda)
            p_emit = p_emit + lam * (p_emit - jax.lax.stop_gradient(p_emit))
        p_blank = logp[..., int(blank)]  # [B, T, U+1]
        neg_inf = jnp.float32(-1e30)

        # alpha[u] over scan of t; within each t, a cumulative scan over u
        def time_step(alpha, t):
            # blank transition from (t-1, u); emit transition from (t, u-1)
            from_blank = alpha + p_blank[:, t - 1, :]

            # sequential in u: alpha_new[u] = logaddexp(from_blank[u], alpha_new[u-1] + emit[t, u-1])
            def u_scan(carry, u):
                val = jnp.logaddexp(from_blank[:, u], carry + p_emit[:, t, u - 1])
                return val, val

            a0 = from_blank[:, 0]
            _, rest = jax.lax.scan(u_scan, a0, jnp.arange(1, U1))
            alpha_new = jnp.concatenate([a0[:, None], rest.T], axis=1)
            return alpha_new, None

        # t = 0 row: only emit transitions
        def u_scan0(carry, u):
            val = carry + p_emit[:, 0, u - 1]
            return val, val

        a00 = jnp.zeros((B,), jnp.float32)
        _, rest0 = jax.lax.scan(u_scan0, a00, jnp.arange(1, U1))
        alpha = jnp.concatenate([a00[:, None], rest0.T], axis=1)

        def body(alpha, t):
            new, _ = time_step(alpha, t)
            return new, new

        _, alphas = jax.lax.scan(body, alpha, jnp.arange(1, T))
        all_alphas = jnp.concatenate([alpha[None], alphas], axis=0)  # [T, B, U+1]
        # final: alpha[tlen-1, ulen] + blank at (tlen-1, ulen)
        ti = jnp.clip(tlen.astype(jnp.int32) - 1, 0, T - 1)
        ui = jnp.clip(ulen.astype(jnp.int32), 0, U)
        bidx = jnp.arange(B)
        final_alpha = all_alphas[ti, bidx, ui]
        final_blank = p_blank[bidx, ti, ui]
        nll = -(final_alpha + final_blank)
        return _reduce(nll, reduction).astype(logits.dtype)

    return apply("rnnt_loss", _fn, input, label, input_lengths, label_lengths)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0, scale=64.0, group=None, return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (reference:
    python/paddle/nn/functional/loss.py margin_cross_entropy,
    paddle/phi/kernels/gpu/margin_cross_entropy_kernel.cu):
    logit_y -> cos(m1*theta + m2) - m3, scaled.  Class-parallel sharding is
    expressed via GSPMD on the logits instead of a manual comm group."""
    logits, label = ensure_tensor(logits), ensure_tensor(label)

    def _fn(x, y):
        xf = x.astype(jnp.float32)
        yi = y.astype(jnp.int32).reshape(-1)
        cos_y = jnp.clip(jnp.take_along_axis(xf, yi[:, None], axis=1), -1.0, 1.0)
        theta = jnp.arccos(cos_y)
        target = jnp.cos(jnp.float32(margin1) * theta + jnp.float32(margin2)) - jnp.float32(margin3)
        onehot = jax.nn.one_hot(yi, x.shape[1], dtype=jnp.float32)
        out = (xf * (1 - onehot) + target * onehot) * jnp.float32(scale)
        logp = jax.nn.log_softmax(out, axis=1)
        nll = -jnp.take_along_axis(logp, yi[:, None], axis=1)[:, 0]
        loss = _reduce(nll, reduction)
        if return_softmax:
            return loss.astype(x.dtype), jnp.exp(logp).astype(x.dtype)
        return loss.astype(x.dtype)

    return apply("margin_cross_entropy", _fn, logits, label, n_outputs=2 if return_softmax else None)


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC negative-class sampling (reference:
    python/paddle/nn/functional/common.py class_center_sample,
    paddle/phi/kernels/gpu/class_center_sample_kernel.cu): keep all positive
    classes, sample negatives to num_samples total; returns (remapped_label,
    sampled_class_centers).  Host-side sampling op (data-dependent sizes),
    eager only — like the reference's usage in the data path."""
    import numpy as np  # host op

    label = ensure_tensor(label)
    y = np.asarray(label._value).astype(np.int64)
    C, S = int(num_classes), int(num_samples)
    pos = np.unique(y)
    if len(pos) >= S:
        sampled = pos
    else:
        # fresh negatives every call, seeded from the framework PRNG stream
        # so paddle.seed reproduces runs
        from paddle_tpu._core import random as _rng

        seed_bits = int(np.asarray(jax.random.randint(_rng.next_key(), (), 0, 2**31 - 1)))
        rng_ = np.random.default_rng(seed_bits)
        neg_pool = np.setdiff1d(np.arange(C, dtype=np.int64), pos, assume_unique=True)
        extra = rng_.choice(neg_pool, size=S - len(pos), replace=False)
        sampled = np.concatenate([pos, extra])
    remap = -np.ones(C, np.int64)
    remap[sampled] = np.arange(len(sampled))
    from paddle_tpu.tensor._ops_common import Tensor as _T

    return _T(jnp.asarray(remap[y].astype(np.int32))), _T(jnp.asarray(sampled.astype(np.int32)))
