"""Spatial-transformer functionals (reference:
python/paddle/nn/functional/vision.py affine_grid/grid_sample;
paddle/phi/kernels/gpu/affine_grid_kernel.cu, grid_sample_kernel.cu).

Pure-jnp gather math: XLA lowers the bilinear gathers to vectorized
dynamic-slices; there is no CUDA texture unit to replicate on TPU.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor

__all__ = ["affine_grid", "grid_sample"]


def _lin(n, align_corners):
    # normalized coords in [-1, 1] for n sample positions
    if align_corners:
        return jnp.linspace(-1.0, 1.0, n)
    step = 2.0 / n
    return jnp.linspace(-1.0 + step / 2.0, 1.0 - step / 2.0, n)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] + out_shape [N, C, H, W] -> grid [N, H, W, 2];
    3-D variant: theta [N, 3, 4] -> grid [N, D, H, W, 3]."""
    theta = ensure_tensor(theta)
    sh = [int(s) for s in (out_shape.tolist() if hasattr(out_shape, "tolist") else out_shape)]
    is_3d = len(sh) == 5

    def _fn(th):
        if is_3d:
            _, _, D, H, W = sh
            zs, ys, xs = _lin(D, align_corners), _lin(H, align_corners), _lin(W, align_corners)
            z, y, x = jnp.meshgrid(zs, ys, xs, indexing="ij")
            base = jnp.stack([x, y, z, jnp.ones_like(x)], axis=-1)  # [D,H,W,4]
            g = jnp.einsum("dhwk,nik->ndhwi", base, th.astype(jnp.float32))
        else:
            _, _, H, W = sh
            ys, xs = _lin(H, align_corners), _lin(W, align_corners)
            y, x = jnp.meshgrid(ys, xs, indexing="ij")
            base = jnp.stack([x, y, jnp.ones_like(x)], axis=-1)  # [H,W,3]
            g = jnp.einsum("hwk,nik->nhwi", base, th.astype(jnp.float32))
        return g.astype(th.dtype)

    return apply("affine_grid", _fn, theta)


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(ix, low, high):
    # reflect coordinates into [low, high] (inclusive), repeating as needed
    span = high - low
    if span <= 0:
        return jnp.zeros_like(ix)
    ix = jnp.abs(ix - low) % (2 * span)
    return low + jnp.where(ix > span, 2 * span - ix, ix)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    """x [N, C, H, W], grid [N, Hg, Wg, 2] (xy order, normalized) ->
    [N, C, Hg, Wg].  Modes: bilinear | nearest; padding: zeros | border |
    reflection."""
    x, grid = ensure_tensor(x), ensure_tensor(grid)

    def _fn(v, g):
        N, C, H, W = v.shape
        gf = g.astype(jnp.float32)
        ix = _unnormalize(gf[..., 0], W, align_corners)
        iy = _unnormalize(gf[..., 1], H, align_corners)

        if padding_mode == "border":
            ix = jnp.clip(ix, 0, W - 1)
            iy = jnp.clip(iy, 0, H - 1)
        elif padding_mode == "reflection":
            if align_corners:
                ix = _reflect(ix, 0.0, float(W - 1))
                iy = _reflect(iy, 0.0, float(H - 1))
            else:
                ix = jnp.clip(_reflect(ix, -0.5, W - 0.5), 0, W - 1)
                iy = jnp.clip(_reflect(iy, -0.5, H - 0.5), 0, H - 1)

        def gather(yy, xx):
            # returns [N, C, Hg, Wg] of v[n, :, yy, xx] with zero padding OOB
            inb = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            flat = v.reshape(N, C, H * W)
            lin = (yc * W + xc).reshape(N, 1, -1)
            out = jnp.take_along_axis(flat, jnp.broadcast_to(lin, (N, C, lin.shape[-1])), axis=2)
            out = out.reshape(N, C, *yy.shape[1:])
            return jnp.where(inb[:, None], out, jnp.zeros((), v.dtype))

        if mode == "nearest":
            return gather(jnp.round(iy), jnp.round(ix))

        x0, y0 = jnp.floor(ix), jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wa = ((x1 - ix) * (y1 - iy))[:, None]
        wb = ((x1 - ix) * (iy - y0))[:, None]
        wc = ((ix - x0) * (y1 - iy))[:, None]
        wd = ((ix - x0) * (iy - y0))[:, None]
        va, vb = gather(y0, x0), gather(y1, x0)
        vc, vd = gather(y0, x1), gather(y1, x1)
        out = va * wa.astype(v.dtype) + vb * wb.astype(v.dtype) + vc * wc.astype(v.dtype) + vd * wd.astype(v.dtype)
        return out

    return apply("grid_sample", _fn, x, grid)
