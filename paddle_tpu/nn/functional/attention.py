"""Attention functionals.

Reference surface: paddle.nn.functional.scaled_dot_product_attention backed by
flash-attention CUDA kernels (paddle/phi/kernels/gpu/flash_attn_kernel.cu).
TPU-native: jax.nn.dot_product_attention by default, with a Pallas
flash-attention kernel (paddle_tpu.ops.flash_attention) for the fused path.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    """Inputs are [batch, seq, heads, head_dim] (paddle flash-attn layout)."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)

    def _sdpa(q, k, v, *rest):
        # jax.nn.dot_product_attention expects BSNH as well.
        mask = rest[0] if rest else None
        if mask is None:
            from paddle_tpu import ops as _ops

            if _ops.use_pallas():
                return _ops.flash_attention(q, k, v, causal=bool(is_causal))
        bias = None
        if mask is not None and mask.dtype != jnp.bool_:
            bias = mask
            mask = None
        out = jax.nn.dot_product_attention(
            q,
            k,
            v,
            bias=bias,
            mask=mask,
            is_causal=bool(is_causal),
        )
        return out

    extra = [ensure_tensor(attn_mask)] if attn_mask is not None else []
    out = apply("scaled_dot_product_attention", _sdpa, query, key, value, *extra)
    if dropout_p > 0.0 and training:
        from .common import dropout

        out = dropout(out, dropout_p, training=training)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, fixed_seed_offset=None, rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity: returns
    (out, softmax_lse placeholder)."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    return out, None


def sdpa_reference(q, k, v, mask=None, is_causal=False, scale=None):
    """Pure-jnp reference used by tests and as the flash-attn numerics oracle."""
    # q,k,v: [B, S, N, H] -> compute in [B, N, S, H]
    q = jnp.swapaxes(q, 1, 2)
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bnqh,bnkh->bnqk", q, k) * s
    if is_causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnqk,bnkh->bnqh", probs, v)
    return jnp.swapaxes(out, 1, 2)
