"""Attention functionals.

Reference surface: paddle.nn.functional.scaled_dot_product_attention backed by
flash-attention CUDA kernels (paddle/phi/kernels/gpu/flash_attn_kernel.cu).
TPU-native: jax.nn.dot_product_attention by default, with a Pallas
flash-attention kernel (paddle_tpu.ops.flash_attention) for the fused path.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    """Inputs are [batch, seq, heads, head_dim] (paddle flash-attn layout)."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)

    def _sdpa(q, k, v, *rest, remat_core=False):
        # jax.nn.dot_product_attention expects BSNH as well.
        mask0 = rest[0] if rest else None

        def _core(q, k, v, mask):
            if mask is None and _SDPBackendState.enable_flash:
                from paddle_tpu import ops as _ops

                if _ops.use_pallas():
                    return _ops.flash_attention(q, k, v, causal=bool(is_causal))
            if not (_SDPBackendState.enable_math
                    or _SDPBackendState.enable_mem_efficient):
                # the XLA einsum path plays both the math and mem-efficient
                # roles; with both disabled there is no backend left for this
                # call (masked, or flash unavailable) — raise like the
                # reference's kernel-dispatch failure instead of silently
                # running a disabled backend
                raise RuntimeError(
                    "scaled_dot_product_attention: no enabled backend can "
                    "serve this call (flash cannot take an attn_mask / is "
                    "unavailable, and math+mem_efficient are disabled by "
                    "sdp_kernel)")
            bias = None
            if mask is not None and mask.dtype != jnp.bool_:
                bias = mask
                mask = None
            causal = bool(is_causal)
            if causal and q.shape[1] != k.shape[1]:
                # jax.nn.dot_product_attention's is_causal is TOP-LEFT aligned;
                # cross lengths (chunked prefill / speculative verify: query
                # chunk against a longer cache) need the bottom-right
                # convention — build it explicitly (matches the flash kernel)
                tri = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool),
                               k=k.shape[1] - q.shape[1])[None, None]
                mask = tri if mask is None else jnp.logical_and(mask, tri)
                causal = False
            return jax.nn.dot_product_attention(
                q,
                k,
                v,
                bias=bias,
                mask=mask,
                is_causal=causal,
            )

        # recompute_granularity="core_attn": the softmax(qk)v core runs
        # under jax.checkpoint so its probabilities rematerialize in
        # backward instead of being saved
        run = jax.checkpoint(_core) if remat_core else _core
        return run(q, k, v, mask0)

    from paddle_tpu.nn.layer.stack import current_recompute_tier

    extra = [ensure_tensor(attn_mask)] if attn_mask is not None else []
    # rides kwargs (static) so the dispatch cache / static capture key on it
    remat_core = current_recompute_tier() == "core_attn"
    out = apply("scaled_dot_product_attention", _sdpa, query, key, value,
                *extra, remat_core=remat_core)
    if dropout_p > 0.0 and training:
        from .common import dropout

        out = dropout(out, dropout_p, training=training)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, fixed_seed_offset=None, rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity: returns
    (out, softmax_lse placeholder)."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    return out, None


def sdpa_reference(q, k, v, mask=None, is_causal=False, scale=None):
    """Pure-jnp reference used by tests and as the flash-attn numerics oracle."""
    # q,k,v: [B, S, N, H] -> compute in [B, N, S, H]
    q = jnp.swapaxes(q, 1, 2)
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bnqh,bnkh->bnqk", q, k) * s
    if is_causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnqk,bnkh->bnqh", probs, v)
    return jnp.swapaxes(out, 1, 2)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns, key_padding_mask=None, attn_mask=None, name=None):
    """CSR-masked attention (reference:
    python/paddle/nn/functional/sparse_attention.py,
    paddle/phi/kernels/gpu/sparse_attention kernels): each query row attends
    only to the CSR-listed key columns.

    TPU-native: the CSR pattern is scattered into a dense additive mask and
    the matmuls stay dense on the MXU — on TPU, structured sparsity below
    ~90% is faster dense; genuinely long sequences should use the Pallas
    flash/ring kernels (paddle_tpu.ops) instead.
    q/k/v: [B, H, S, D]; offset: [B, H, S+1]; columns: [B, H, nnz].
    """
    from paddle_tpu.tensor._ops_common import apply as _apply, ensure_tensor as _et

    query, key, value = _et(query), _et(key), _et(value)
    off, cols = _et(sparse_csr_offset), _et(sparse_csr_columns)

    def _fn(q, k, v, offv, colv):
        B, H, S, D = q.shape
        nnz = colv.shape[-1]
        # row id of each nnz entry: searchsorted over the offset vector
        pos = jnp.arange(nnz, dtype=jnp.int32)
        rows = jax.vmap(jax.vmap(lambda o: jnp.searchsorted(o[1:], pos, side="right")))(
            offv.astype(jnp.int32)
        )  # [B, H, nnz]
        mask = jnp.full((B, H, S, S), -jnp.inf, jnp.float32)
        bidx = jnp.arange(B)[:, None, None]
        hidx = jnp.arange(H)[None, :, None]
        mask = mask.at[bidx, hidx, rows, colv.astype(jnp.int32)].set(0.0)
        scores = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32), k.astype(jnp.float32))
        scores = scores / jnp.sqrt(jnp.float32(D)) + mask
        p = jax.nn.softmax(scores, axis=-1)
        p = jnp.where(jnp.isnan(p), 0.0, p)  # rows with no allowed columns
        return jnp.einsum("bhst,bhtd->bhsd", p, v.astype(jnp.float32)).astype(q.dtype)

    return _apply("sparse_attention", _fn, query, key, value, off, cols)


class _SDPBackendState:
    enable_math = True
    enable_flash = True
    enable_mem_efficient = True


def sdp_kernel(enable_math=False, enable_flash=True,
               enable_mem_efficient=True):
    """Context manager selecting the scaled-dot-product backend (reference
    nn/functional/flash_attention.py sdp_kernel).  TPU-native mapping:
    'flash' = the Pallas kernel path, 'math'/'mem_efficient' = the XLA
    einsum path (XLA's fusion IS the memory-efficient tier); disabling
    every backend raises at entry like the reference's kernel-dispatch
    failure, but eagerly and readably."""
    import contextlib

    if not (enable_math or enable_flash or enable_mem_efficient):
        raise ValueError("sdp_kernel: at least one backend must be enabled")

    @contextlib.contextmanager
    def _ctx():
        prev = (_SDPBackendState.enable_math, _SDPBackendState.enable_flash,
                _SDPBackendState.enable_mem_efficient)
        _SDPBackendState.enable_math = enable_math
        _SDPBackendState.enable_flash = enable_flash
        _SDPBackendState.enable_mem_efficient = enable_mem_efficient
        try:
            yield
        finally:
            (_SDPBackendState.enable_math, _SDPBackendState.enable_flash,
             _SDPBackendState.enable_mem_efficient) = prev

    return _ctx()


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """Packed-QKV flash attention (reference flash_attn_qkvpacked):
    qkv is [B, S, 3, N, H]."""
    qkv = ensure_tensor(qkv)
    from paddle_tpu.tensor.manipulation import squeeze, split

    q, k, v = (squeeze(t, axis=2) for t in split(qkv, 3, axis=2))
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen flash attention over packed sequences (reference
    flash_attn_unpadded): query/key/value are [total, N, H] with
    cumulative sequence offsets (cu_seqlens, the LoD vector).

    TPU-native: the ragged batch is masked block-diagonally in one jit
    region — XLA keeps the matmuls dense on the MXU; sequences never
    attend across boundaries.  Returns (out, None) like flash_attention.
    """
    import numpy as np

    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    cq = np.asarray(cu_seqlens_q._value if hasattr(cu_seqlens_q, "_value")
                    else cu_seqlens_q, np.int64)
    ck = np.asarray(cu_seqlens_k._value if hasattr(cu_seqlens_k, "_value")
                    else cu_seqlens_k, np.int64)
    if len(cq) != len(ck):
        raise ValueError("flash_attn_unpadded: cu_seqlens_q and cu_seqlens_k "
                         "must describe the same number of sequences")
    tq, tk = int(query.shape[0]), int(key.shape[0])
    if cq[-1] != tq or ck[-1] != tk:
        # padded/mismatched packed buffers would silently let the last
        # sequence attend to garbage pad rows
        raise ValueError(
            f"flash_attn_unpadded: cu_seqlens must cover the packed buffer "
            f"exactly (cu_seqlens_q[-1]={int(cq[-1])} vs {tq} rows, "
            f"cu_seqlens_k[-1]={int(ck[-1])} vs {tk} rows)")

    def _seg(cu, total):
        seg = np.zeros(total, np.int64)
        starts = cu[1:-1]
        np.add.at(seg, starts[starts < total], 1)
        return np.cumsum(seg)

    seg_q, seg_k = _seg(cq, tq), _seg(ck, tk)
    # per-row position within its sequence (for causal alignment); these
    # ride as RUNTIME int32 args, not closure constants — a baked
    # [total_q, total_k] mask would cost O(total^2) host memory and a
    # recompile per distinct packing
    pos_q = np.arange(tq) - cq[seg_q]
    pos_k = np.arange(tk) - ck[seg_k]
    len_q = (cq[1:] - cq[:-1])[seg_q]
    len_k = (ck[1:] - ck[:-1])[seg_k]
    row_q = ensure_tensor(np.stack([seg_q, pos_q, len_q]).astype(np.int32))
    row_k = ensure_tensor(np.stack([seg_k, pos_k, len_k]).astype(np.int32))

    dropout_active = dropout > 0.0 and training
    if dropout_active:  # key at trace time (common.py dropout pattern)
        from paddle_tpu._core import random as _random

        drop_key = _random.next_key()

    def _fn(q, k, v, rq, rk):
        allowed = rq[0][:, None] == rk[0][None, :]
        if causal:
            # bottom-right aligned within each sequence pair
            allowed &= (rq[1][:, None] + (rk[2][None, :] - rq[2][:, None])
                        >= rk[1][None, :])
        s = jnp.einsum("qnh,knh->nqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * jnp.float32(scale)
        s = jnp.where(allowed[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        # a query row with ZERO allowed keys (causal with len_k < len_q)
        # must output zeros, not a uniform average over foreign sequences
        p = jnp.where(allowed.any(axis=1)[None, :, None], p, 0.0)
        if dropout_active:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout, p.shape)
            p = jnp.where(keep, p / (1.0 - dropout), 0.0)
        return jnp.einsum("nqk,knh->qnh", p, v.astype(jnp.float32)).astype(q.dtype)

    out = apply("flash_attn_unpadded", _fn, query, key, value, row_q, row_k)
    return out, None
