"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
fused kernels paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu — on TPU
XLA fuses the reduction+scale chain; a Pallas fused variant lives in
paddle_tpu.incubate for the long-row case)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import Tensor, apply, ensure_tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    x = ensure_tensor(x)
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    nd = len(ns)

    def _ln(v, *rest, epsilon=1e-05):
        axes = tuple(range(v.ndim - nd, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it)
        if bias is not None:
            out = out + next(it)
        return out

    extra = [ensure_tensor(t) for t in (weight, bias) if t is not None]
    # epsilon as a static kwarg: recorded on the Operator, so fusion
    # patterns (AddNormPattern) can read it
    return apply("layer_norm", _ln, x, *extra, epsilon=float(epsilon))


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (no mean subtraction) — the LLaMA-family norm; reference exposes
    it as incubate fused_rms_norm."""
    x = ensure_tensor(x)

    def _rms(v, *rest, epsilon=1e-6):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        if rest:
            out = out * rest[0]
        return out

    extra = [ensure_tensor(weight)] if weight is not None else []
    return apply("rms_norm", _rms, x, *extra, epsilon=float(epsilon))


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    x = ensure_tensor(x)
    running_mean, running_var = ensure_tensor(running_mean), ensure_tensor(running_var)
    channel_last = data_format[-1] == "C" and len(data_format) > 2 or data_format == "NLC" or data_format == "NHWC" or data_format == "NDHWC"
    use_batch_stats = training and not use_global_stats

    def _bn(v, rm, rv, *rest):
        ch_ax = v.ndim - 1 if channel_last else (1 if v.ndim > 1 else 0)
        shape = [1] * v.ndim
        shape[ch_ax] = v.shape[ch_ax]
        if use_batch_stats:
            axes = tuple(d for d in range(v.ndim) if d != ch_ax)
            mean = jnp.mean(v, axis=axes)
            var = jnp.var(v, axis=axes)
        else:
            mean, var = rm, rv
        out = (v - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out, mean, var

    extra = [ensure_tensor(t) for t in (weight, bias) if t is not None]
    out, batch_mean, batch_var = apply("batch_norm", _bn, x, running_mean, running_var, *extra)

    if use_batch_stats:
        # Update running stats in place (reference semantics: stats are
        # buffers mutated during training).
        with_no_grad_update(running_mean, momentum, batch_mean)
        with_no_grad_update(running_var, momentum, batch_var)
    return out


def with_no_grad_update(running, momentum, batch_stat):
    from paddle_tpu._core import autograd as _ag

    # Through the funnel (not raw _value math) so the update also records
    # under static capture, where _value is symbolic.
    with _ag.no_grad():
        new = running * momentum + batch_stat * (1.0 - momentum)
    from paddle_tpu.static import program as _spm

    if _spm.in_static_capture():
        # Register the state write so the executor persists the new value
        # across runs (same mechanism as optimizer param updates).  Do NOT
        # bind the dygraph tensor itself: its concrete value must survive
        # the capture for later eager use.
        from paddle_tpu._core.tensor import Parameter as _Param

        prog = _spm.current_main_program()
        if isinstance(running, _spm.Variable):
            target = running
        elif isinstance(running, _Param):
            target = prog.var_for_parameter(running)
        else:
            target = prog.var_for_state(running)
        prog.add_write(target, new)
    else:
        running._bind(new._value)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C" and len(data_format) > 3

    def _in(v, *rest):
        ch_ax = v.ndim - 1 if channel_last else 1
        axes = tuple(d for d in range(2, v.ndim)) if not channel_last else tuple(d for d in range(1, v.ndim - 1))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        shape = [1] * v.ndim
        shape[ch_ax] = v.shape[ch_ax]
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    extra = [ensure_tensor(t) for t in (weight, bias) if t is not None]
    return apply("instance_norm", _in, x, *extra)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def _gn(v, *rest):
        if channel_last:
            v_t = jnp.moveaxis(v, -1, 1)
        else:
            v_t = v
        n, c = v_t.shape[0], v_t.shape[1]
        sp = v_t.shape[2:]
        g = v_t.reshape(n, num_groups, c // num_groups, *sp)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v_t.shape)
        shape = [1] * v_t.ndim
        shape[1] = c
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    extra = [ensure_tensor(t) for t in (weight, bias) if t is not None]
    return apply("group_norm", _gn, x, *extra)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def _lrn(v):
        ch_ax = 1 if data_format[1] == "C" else v.ndim - 1
        sq = jnp.square(v)
        # sum over a window along channels
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        pads = [(0, 0)] * v.ndim
        pads[ch_ax] = (pad_lo, pad_hi)
        sq_p = jnp.pad(sq, pads)
        acc = jnp.zeros_like(v)
        for i in range(size):
            sl = [slice(None)] * v.ndim
            sl[ch_ax] = slice(i, i + v.shape[ch_ax])
            acc = acc + sq_p[tuple(sl)]
        return v / jnp.power(k + alpha * acc, beta)

    return apply("local_response_norm", _lrn, x)
