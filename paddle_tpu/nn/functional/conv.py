"""Convolutions (reference: python/paddle/nn/functional/conv.py; kernels
paddle/phi/kernels/gpu/conv_kernel.cu → here lax.conv_general_dilated, which
XLA tiles onto the MXU)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import apply, ensure_tensor


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(i) for i in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(i) for i in v)
    return tuple(int(v) for _ in range(n))


def _padding(padding, n, strides, dilations, kernel):
    """Normalize paddle padding spec → lax padding list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    strides = _tuple(stride, nd)
    dilations = _tuple(dilation, nd)
    channel_last = data_format[-1] == "C"
    spatial = "DHW"[-nd:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    out_spec = lhs_spec
    rhs_spec = "OI" + spatial  # weight is [out, in/groups, *k]
    pad_spec = _padding(padding, nd, strides, dilations, weight.shape[2:])
    dn = jax.lax.conv_dimension_numbers(tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, out_spec))

    def _cv(v, w, *rest):
        out = jax.lax.conv_general_dilated(
            v,
            w,
            window_strides=strides,
            padding=pad_spec,
            rhs_dilation=dilations,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply("conv", _cv, x, weight, ensure_tensor(bias))
    return apply("conv", _cv, x, weight)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, nd, data_format, output_size):
    """Reference conv_transpose semantics (paddle/torch):
    out = (in - 1)*s - 2p + d*(k - 1) + output_padding + 1.

    lax.conv_transpose is conv_general_dilated with lhs_dilation=strides
    and a FORWARD-conv padding spec, so the paddle padding p maps to
    lax pads (d*(k-1) - p, d*(k-1) - p + output_padding), with
    transpose_kernel=True for the spatial flip + I/O swap of the adjoint
    (verified element-wise vs torch.conv_transpose{1,2,3}d)."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    strides = _tuple(stride, nd)
    dilations = _tuple(dilation, nd)
    opad = _tuple(output_padding, nd) if output_padding is not None else (0,) * nd
    channel_last = data_format[-1] == "C"
    spatial = "DHW"[-nd:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle weight layout is [in, out/groups, *k]; with transpose_kernel
    # lax wants the FORWARD kernel's spec, whose O axis is our in axis
    rhs_spec = "OI" + spatial
    dn = jax.lax.conv_dimension_numbers(tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, lhs_spec))
    pad_spec = _padding(padding, nd, strides, dilations, weight.shape[2:])
    kernel = [int(k) for k in weight.shape[2:]]
    in_spatial = [int(s) for s in (x.shape[1:-1] if channel_last else x.shape[2:])]

    if not isinstance(pad_spec, str):
        if output_size is not None:
            # paddle: output_size picks the target within the stride-sized
            # ambiguity window — expressed as extra output_padding
            target = [int(s) for s in (output_size if isinstance(output_size, (list, tuple)) else [output_size] * nd)]
            default = [
                (i - 1) * s - (p[0] + p[1]) + d * (k - 1) + 1
                for i, s, p, d, k in zip(in_spatial, strides, pad_spec, dilations, kernel)
            ]
            opad = tuple(t - dflt for t, dflt in zip(target, default))
            for o, s in zip(opad, strides):
                if not 0 <= o < max(s, 1):
                    raise ValueError(
                        f"output_size {target} unreachable: implied "
                        f"output_padding {opad} outside [0, stride)")
        pads = [
            (d * (k - 1) - p[0], d * (k - 1) - p[1] + o)
            for p, o, d, k in zip(pad_spec, opad, dilations, kernel)
        ]
    else:
        pads = pad_spec

    def _cvt(v, w, *rest):
        if groups == 1:
            out = jax.lax.conv_transpose(
                v, w, strides=strides, padding=pads, rhs_dilation=dilations,
                dimension_numbers=dn, transpose_kernel=True,
            )
        else:
            # grouped transpose: split and concat along channel axis
            ch_ax = 1 if not channel_last else v.ndim - 1
            vs = jnp.split(v, groups, axis=ch_ax)
            ws = jnp.split(w, groups, axis=0)
            outs = [
                jax.lax.conv_transpose(
                    vv, ww, strides=strides, padding=pads, rhs_dilation=dilations,
                    dimension_numbers=dn, transpose_kernel=True,
                )
                for vv, ww in zip(vs, ws)
            ]
            out = jnp.concatenate(outs, axis=ch_ax)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    return apply("conv_transpose", _cvt, x, weight, *( [ensure_tensor(bias)] if bias is not None else [] ))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format, output_size)
