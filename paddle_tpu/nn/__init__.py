"""paddle.nn equivalent surface (reference: python/paddle/nn/__init__.py)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer, ParamAttr  # noqa: F401
from . import lora  # noqa: F401,E402
from .lora import AdapterPack, LoRALinear, apply_lora, lora_state_dict  # noqa: F401,E402

from . import quant  # noqa: F401,E402
