"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exporting
tensor/linalg.py).  The implementations live in paddle_tpu.tensor.linalg."""

from paddle_tpu.tensor.linalg import *  # noqa: F401,F403
from paddle_tpu.tensor import linalg as _impl

__all__ = [n for n in dir(_impl) if not n.startswith("_")]
