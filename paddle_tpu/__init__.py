"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface (reference: python/paddle/__init__.py, 387 exports),
built on JAX/XLA/Pallas/pjit rather than ported from the CUDA design.
"""

from __future__ import annotations

# dtypes
from ._core.dtype import (  # noqa: F401
    DType,
    bfloat16,
    bool_ as bool8,
    complex64,
    complex128,
    dtype,
    float16,
    float32,
    float64,
    float8_e4m3fn,
    float8_e5m2,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from ._core.place import (  # noqa: F401
    CPUPlace,
    CustomPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_tpu,
    set_device,
)
from ._core.flags import get_flags, set_flags  # noqa: F401
from ._core.random import get_rng_state, seed, set_rng_state  # noqa: F401
from ._core.tensor import Parameter, Tensor  # noqa: F401
from ._core.autograd import enable_grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from ._core.autograd import grad  # noqa: F401

# Full tensor-op surface (also patches Tensor methods).
from .tensor import *  # noqa: F401,F403
from .tensor import creation as _creation  # noqa: F401

# Common bool dtype name
from ._core import dtype as _dtype_mod

bool = _dtype_mod.bool_  # noqa: A001

# Subpackages land incrementally; import what exists.
import importlib as _importlib

for _sub in (
    "autograd",
    "nn",
    "optimizer",
    "amp",
    "io",
    "device",
    "framework",
    "jit",
    "static",
    "distributed",
    "incubate",
    "metric",
    "vision",
    "inference",
    "hapi",
    "profiler",
    "distribution",
    "sparse",
    "fft",
    "signal",
    "text",
    "audio",
    "geometric",
    "quantization",
    "onnx",
    "cost_model",
    "linalg",
    "utils",
    "decomposition",
):
    try:
        globals()[_sub] = _importlib.import_module(f".{_sub}", __name__)
    except ModuleNotFoundError:
        pass

try:
    from .framework.io_utils import load, save, wait_async_save  # noqa: F401,E402
except ImportError:
    pass
try:
    from .nn.layer.layers import Layer  # noqa: F401,E402
except ImportError:
    pass

__version__ = "0.6.0"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


try:
    from .hapi import Model, summary, flops  # noqa: F401,E402
    from .hapi import callbacks  # noqa: F401,E402
except ImportError:
    pass
from . import regularizer  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import pir  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from .static.program import enable_static, disable_static, in_dynamic_mode  # noqa: F401,E402

# Framework defaults / dtype info / compat surface (reference top-level names)
from .framework.defaults import (  # noqa: F401,E402
    LazyGuard,
    batch,
    check_shape,
    create_parameter,
    disable_signal_handler,
    finfo,
    get_default_dtype,
    iinfo,
    set_default_dtype,
    set_printoptions,
)
from ._core.place import CUDAPinnedPlace, CUDAPlace  # noqa: F401,E402
from .nn.layer.layers import ParamAttr  # noqa: F401,E402
from .distributed import DataParallel  # noqa: F401,E402

# CUDA-named RNG state APIs are the generic device generator state here.
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def tolist(x):
    """paddle.tolist parity: nested Python list of the tensor's values."""
    from ._core.tensor import Tensor

    return x.tolist() if isinstance(x, Tensor) else Tensor(x).tolist()
