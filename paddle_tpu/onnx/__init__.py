"""paddle.onnx.export equivalent.

Reference: python/paddle/onnx/export.py (delegates to the external
paddle2onnx converter over a saved static Program).  TPU-native: the model's
forward is traced to a JAXPR — the same capture jit/to_static uses — and the
jaxpr's primitives are converted to ONNX ops directly; serialization is the
self-contained writer in _proto.py (no onnx/protobuf dependency, matching
this image).  Covered: the MLP/transformer primitive families (dot_general,
elementwise, activations, reductions, reshape/transpose/broadcast/concat/
slice, select, cast, softmax patterns emerge from these).  Unsupported
primitives raise with the op name — the honest boundary, like paddle2onnx's
unconvertible-op errors.
"""

from __future__ import annotations

import numpy as np

from . import _proto as P

__all__ = ["export"]


def _np(v):
    return np.asarray(v)


class _Converter:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.names = {}
        self.counter = [0]

    def fresh(self, hint="t"):
        self.counter[0] += 1
        return f"{hint}_{self.counter[0]}"

    def name_of(self, var):
        from jax._src.core import Literal

        if isinstance(var, Literal):
            n = self.fresh("const")
            self.initializers.append(P.tensor_proto(n, _np(var.val)))
            return n
        if var not in self.names:
            self.names[var] = self.fresh("v")
        return self.names[var]

    def add_const(self, arr, hint="const"):
        n = self.fresh(hint)
        self.initializers.append(P.tensor_proto(n, _np(arr)))
        return n

    def emit(self, op, inputs, n_out=1, attrs=(), hint=None):
        outs = [self.fresh(hint or op.lower()) for _ in range(n_out)]
        self.nodes.append(P.node(op, inputs, outs, attrs=list(attrs)))
        return outs[0] if n_out == 1 else outs


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "neg": "Neg",
    "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign", "floor": "Floor",
    "ceil": "Ceil", "round": "Round", "erf": "Erf", "logistic": "Sigmoid",
    "sin": "Sin", "cos": "Cos", "not": "Not", "and": "And", "or": "Or",
}
_COMPARE = {"eq": "Equal", "gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual", "le": "LessOrEqual"}
_REDUCE = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax", "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}


def _convert_eqn(cv: _Converter, eqn):
    prim = eqn.primitive.name
    ins = [cv.name_of(v) for v in eqn.invars]
    out = eqn.outvars[0]

    def bind(name):
        cv.names[out] = name

    if prim in _ELEMENTWISE:
        bind(cv.emit(_ELEMENTWISE[prim], ins))
    elif prim in _COMPARE:
        bind(cv.emit(_COMPARE[prim], ins))
    elif prim in _REDUCE:
        keep = P.attr_int("keepdims", 0)
        if prim == "reduce_sum":
            # opset 13: ReduceSum takes axes as an input; the others keep the
            # axes ATTRIBUTE until opset 18
            axes = cv.add_const(np.asarray(eqn.params["axes"], np.int64), "axes")
            bind(cv.emit("ReduceSum", [ins[0], axes], attrs=[keep]))
        else:
            bind(cv.emit(_REDUCE[prim], [ins[0]],
                         attrs=[P.attr_ints("axes", eqn.params["axes"]), keep]))
    elif prim == "integer_pow":
        y = cv.add_const(np.asarray(float(eqn.params["y"]), _np(eqn.invars[0].aval.dtype).dtype), "exp")
        bind(cv.emit("Pow", [ins[0], y]))
    elif prim == "rsqrt":
        s = cv.emit("Sqrt", [ins[0]])
        one = cv.add_const(np.asarray(1.0, eqn.invars[0].aval.dtype), "one")
        bind(cv.emit("Div", [one, s]))
    elif prim == "convert_element_type":
        to = P.np_to_onnx_dtype(np.dtype(eqn.params["new_dtype"]))
        bind(cv.emit("Cast", ins, attrs=[P.attr_int("to", to)]))
    elif prim == "reshape":
        shape = cv.add_const(np.asarray(eqn.params["new_sizes"], np.int64), "shape")
        bind(cv.emit("Reshape", [ins[0], shape]))
    elif prim == "transpose":
        bind(cv.emit("Transpose", ins, attrs=[P.attr_ints("perm", eqn.params["permutation"])]))
    elif prim == "broadcast_in_dim":
        in_aval = eqn.invars[0].aval
        shape = eqn.params["shape"]
        bdims = eqn.params["broadcast_dimensions"]
        # insert singleton axes so ranks match, then Expand
        mid_shape = [1] * len(shape)
        for src, dst in enumerate(bdims):
            mid_shape[dst] = in_aval.shape[src] if in_aval.shape else 1
        rs = cv.add_const(np.asarray(mid_shape, np.int64), "shape")
        mid = cv.emit("Reshape", [ins[0], rs])
        tgt = cv.add_const(np.asarray(shape, np.int64), "shape")
        bind(cv.emit("Expand", [mid, tgt]))
    elif prim == "dot_general":
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        l_aval, r_aval = eqn.invars[0].aval, eqn.invars[1].aval
        lr, rr = len(l_aval.shape), len(r_aval.shape)
        # support the matmul-like family: single contraction, batch prefix
        if len(lc) == 1 and len(rc) == 1 and list(lb) == list(range(len(lb))) and list(rb) == list(range(len(rb))):
            a, b = ins
            if lc[0] != lr - 1:  # contract dim must be last for lhs
                perm = [d for d in range(lr) if d != lc[0]] + [lc[0]]
                a = cv.emit("Transpose", [a], attrs=[P.attr_ints("perm", perm)])
            if rc[0] != len(lb):  # contract dim must be first non-batch for rhs
                perm = list(rb) + [rc[0]] + [d for d in range(rr) if d != rc[0] and d not in rb]
                b = cv.emit("Transpose", [b], attrs=[P.attr_ints("perm", perm)])
            bind(cv.emit("MatMul", [a, b]))
        else:
            raise NotImplementedError(
                f"onnx export: dot_general with dimension_numbers {eqn.params['dimension_numbers']}"
            )
    elif prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("onnx export: select_n with >2 cases")
        # jax select_n(pred, false, true) -> Where(pred, true, false)
        bind(cv.emit("Where", [ins[0], ins[2], ins[1]]))
    elif prim == "concatenate":
        bind(cv.emit("Concat", ins, attrs=[P.attr_int("axis", eqn.params["dimension"])]))
    elif prim == "slice":
        starts = cv.add_const(np.asarray(eqn.params["start_indices"], np.int64), "starts")
        ends = cv.add_const(np.asarray(eqn.params["limit_indices"], np.int64), "ends")
        axes = cv.add_const(np.asarray(range(len(eqn.params["start_indices"])), np.int64), "axes")
        args = [ins[0], starts, ends, axes]
        if eqn.params.get("strides") is not None:
            args.append(cv.add_const(np.asarray(eqn.params["strides"], np.int64), "steps"))
        bind(cv.emit("Slice", args))
    elif prim == "squeeze":
        axes = cv.add_const(np.asarray(eqn.params["dimensions"], np.int64), "axes")
        bind(cv.emit("Squeeze", [ins[0], axes]))
    elif prim == "rev":
        raise NotImplementedError("onnx export: lax.rev")
    elif prim == "gather":
        # one-axis take: common embedding/index_select pattern
        dn = eqn.params["dimension_numbers"]
        if len(dn.start_index_map) == 1 and len(dn.collapsed_slice_dims) == 1 \
                and dn.start_index_map == dn.collapsed_slice_dims:
            axis = dn.start_index_map[0]
            idx = ins[1]
            # jax indices carry a trailing singleton dim; squeeze it
            idx_aval = eqn.invars[1].aval
            if idx_aval.shape and idx_aval.shape[-1] == 1:
                ax = cv.add_const(np.asarray([len(idx_aval.shape) - 1], np.int64), "axes")
                idx = cv.emit("Squeeze", [idx, ax])
            bind(cv.emit("Gather", [ins[0], idx], attrs=[P.attr_int("axis", axis)]))
        else:
            raise NotImplementedError(f"onnx export: general gather {dn}")
    elif prim == "stop_gradient":
        bind(cv.emit("Identity", ins))
    elif prim == "custom_jvp_call" or prim == "custom_vjp_call" or prim == "pjit" or prim == "jit":
        # inline the sub-jaxpr
        sub = eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
        jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        consts = getattr(sub, "consts", getattr(sub, "literals", []))
        for cvv, cval in zip(jaxpr.constvars, consts):
            cv.names[cvv] = cv.add_const(cval, "w")
        for iv, n in zip(jaxpr.invars, ins):
            cv.names[iv] = n
        for sub_eqn in jaxpr.eqns:
            _convert_eqn(cv, sub_eqn)
        for ov_out, ov_in in zip(eqn.outvars, jaxpr.outvars):
            cv.names[ov_out] = cv.name_of(ov_in)
        return
    else:
        raise NotImplementedError(f"onnx export: unsupported primitive '{prim}'")

    # multi-output prims in the supported set are single-output; map extras
    for extra in eqn.outvars[1:]:
        cv.names[extra] = cv.name_of(out)


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export a Layer (or callable) to `path + '.onnx'`.

    input_spec: list of paddle.static.InputSpec (or Tensors/arrays giving
    example shapes).  Returns the output path.
    """
    import jax

    from paddle_tpu._core.autograd import no_grad
    from paddle_tpu._core.tensor import Tensor
    from paddle_tpu.static import InputSpec

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")

    examples = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            from paddle_tpu._core.dtype import to_jax_dtype

            shape = [1 if d in (None, -1) else int(d) for d in s.shape]
            examples.append(jax.ShapeDtypeStruct(tuple(shape), to_jax_dtype(s.dtype)))
        elif isinstance(s, Tensor):
            examples.append(jax.ShapeDtypeStruct(s._value.shape, s._value.dtype))
        else:
            a = np.asarray(s)
            examples.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        def fwd(*vals):
            with no_grad():
                out = layer(*[Tensor(v) for v in vals])
            leaves = jax.tree_util.tree_leaves(out, is_leaf=lambda x: isinstance(x, Tensor))
            return [l._value if isinstance(l, Tensor) else l for l in leaves]

        closed = jax.make_jaxpr(fwd)(*examples)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    cv = _Converter()
    jaxpr = closed.jaxpr
    graph_inputs = []
    for i, (var, ex) in enumerate(zip(jaxpr.invars, examples)):
        n = f"input_{i}"
        cv.names[var] = n
        graph_inputs.append(P.value_info(n, P.np_to_onnx_dtype(ex.dtype), ex.shape))
    for cvv, cval in zip(jaxpr.constvars, closed.consts):
        cv.names[cvv] = cv.add_const(cval, "w")
    for eqn in jaxpr.eqns:
        _convert_eqn(cv, eqn)
    graph_outputs = []
    for i, ov in enumerate(jaxpr.outvars):
        n = cv.name_of(ov)
        graph_outputs.append(P.value_info(n, P.np_to_onnx_dtype(ov.aval.dtype), ov.aval.shape))

    g = P.graph(cv.nodes, "paddle_tpu_graph", cv.initializers, graph_inputs, graph_outputs)
    buf = P.model(g, opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(buf)
    return out_path
