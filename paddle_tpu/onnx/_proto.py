"""Minimal protobuf wire-format writer + the ONNX message subset.

The reference's paddle.onnx.export delegates to the external paddle2onnx
package (python/paddle/onnx/export.py); this image has no onnx/protobuf
libraries, so the exporter serializes ModelProto directly — the wire format
(varints + length-delimited fields, field numbers from onnx.proto3) is
stable and self-contained.  A reader (`parse_model`) decodes the same subset
for verification.
"""

from __future__ import annotations

import struct

# onnx TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL = 1, 2, 3, 6, 7, 9
FLOAT16, DOUBLE, BFLOAT16 = 10, 11, 16

_NP2ONNX = {
    "float32": FLOAT,
    "uint8": UINT8,
    "int8": INT8,
    "int32": INT32,
    "int64": INT64,
    "bool": BOOL,
    "float16": FLOAT16,
    "float64": DOUBLE,
    "bfloat16": BFLOAT16,
}


def np_to_onnx_dtype(dt) -> int:
    name = str(dt)
    if name not in _NP2ONNX:
        raise ValueError(f"onnx export: unsupported dtype {name}")
    return _NP2ONNX[name]


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(int(value))


def f_bytes(field: int, data: bytes) -> bytes:
    return _key(field, 2) + _varint(len(data)) + data


def f_string(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode())


def f_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", float(v))


# ---------------------------------------------------------------- messages


def tensor_proto(name, arr) -> bytes:
    import numpy as np

    a = np.asarray(arr)
    dt = np_to_onnx_dtype(a.dtype)
    body = b"".join(f_varint(1, int(d)) for d in a.shape)
    body += f_varint(2, dt)
    body += f_string(8, name)
    body += f_bytes(9, a.tobytes())  # raw_data
    return body


def attr_int(name, v) -> bytes:
    return f_string(1, name) + f_varint(3, v) + f_varint(20, 2)  # type=INT


def attr_ints(name, vals) -> bytes:
    return f_string(1, name) + b"".join(f_varint(8, v) for v in vals) + f_varint(20, 7)


def attr_float(name, v) -> bytes:
    return f_string(1, name) + f_float(2, v) + f_varint(20, 1)


def attr_string(name, s) -> bytes:
    return f_string(1, name) + f_bytes(4, s.encode()) + f_varint(20, 3)


def node(op_type, inputs, outputs, name="", attrs=()) -> bytes:
    body = b"".join(f_string(1, i) for i in inputs)
    body += b"".join(f_string(2, o) for o in outputs)
    if name:
        body += f_string(3, name)
    body += f_string(4, op_type)
    body += b"".join(f_bytes(5, a) for a in attrs)
    return body


def value_info(name, dtype_onnx, shape) -> bytes:
    dims = b"".join(f_bytes(1, f_varint(1, int(d))) for d in shape)  # dim_value
    shape_proto = dims
    tensor_type = f_varint(1, dtype_onnx) + f_bytes(2, shape_proto)
    type_proto = f_bytes(1, tensor_type)
    return f_string(1, name) + f_bytes(2, type_proto)


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    body = b"".join(f_bytes(1, n) for n in nodes)
    body += f_string(2, name)
    body += b"".join(f_bytes(5, t) for t in initializers)
    body += b"".join(f_bytes(11, vi) for vi in inputs)
    body += b"".join(f_bytes(12, vi) for vi in outputs)
    return body


def model(graph_bytes, opset=13, producer="paddle_tpu") -> bytes:
    opset_id = f_string(1, "") + f_varint(2, opset)
    body = f_varint(1, 8)  # ir_version 8
    body += f_string(2, producer)
    body += f_bytes(7, graph_bytes)
    body += f_bytes(8, opset_id)
    return body


# ---------------------------------------------------------------- reader


def _read_varint(buf, i):
    shift = 0
    out = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def parse_fields(buf):
    """Decode one message level -> list of (field, wire, value)."""
    i = 0
    out = []
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i : i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i : i + 4])[0]
            i += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[i : i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.append((field, wire, v))
    return out


def parse_model(buf):
    """Structural decode of a serialized ModelProto (verification aid)."""
    out = {"nodes": [], "initializers": [], "inputs": [], "outputs": [], "opset": None}
    for field, _, v in parse_fields(buf):
        if field == 7:  # graph
            for gf, _, gv in parse_fields(v):
                if gf == 1:
                    nd = {"inputs": [], "outputs": [], "op_type": None}
                    for nf, _, nv in parse_fields(gv):
                        if nf == 1:
                            nd["inputs"].append(nv.decode())
                        elif nf == 2:
                            nd["outputs"].append(nv.decode())
                        elif nf == 4:
                            nd["op_type"] = nv.decode()
                    out["nodes"].append(nd)
                elif gf == 5:
                    name = dims = dtype = None
                    dims = []
                    for tf, _, tv in parse_fields(gv):
                        if tf == 1:
                            dims.append(tv)
                        elif tf == 2:
                            dtype = tv
                        elif tf == 8:
                            name = tv.decode()
                    out["initializers"].append({"name": name, "dims": dims, "dtype": dtype})
                elif gf == 11:
                    out["inputs"].append(_vi_name(gv))
                elif gf == 12:
                    out["outputs"].append(_vi_name(gv))
        elif field == 8:
            for of, _, ov in parse_fields(v):
                if of == 2:
                    out["opset"] = ov
    return out


def _vi_name(buf):
    for f, _, v in parse_fields(buf):
        if f == 1:
            return v.decode()
    return None
