"""Native runtime components (C++), loaded via ctypes.

The reference implements its runtime substrate in C++ (TCPStore
paddle/phi/core/distributed/store/tcp_store.h, shared-memory dataloader
queues, HostEventRecorder paddle/fluid/platform/profiler/).  This package
builds `libpaddle_tpu_native.so` from src/*.cc at first import (g++, cached
by source hash) and exposes:

- TCPStoreServer / TCPStoreClient — rendezvous bootstrap store
- ShmRing — process-shared ring buffer (DataLoader worker transport)
- HostEventRecorder — low-overhead profiler span buffer

If no compiler is available the attribute `AVAILABLE` is False and callers
fall back to pure-Python equivalents.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import random
import subprocess
import threading
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src")

AVAILABLE = False
_lib = None


def _build() -> str | None:
    srcs = sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC) if f.endswith(".cc")
    )
    h = hashlib.sha256()
    for s in srcs:
        h.update(open(s, "rb").read())
    tag = h.hexdigest()[:16]
    cache_dir = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    os.makedirs(cache_dir, exist_ok=True)
    out = os.path.join(cache_dir, f"libpaddle_tpu_native-{tag}.so")
    if os.path.exists(out):
        return out
    tmp = f"{out}.{os.getpid()}.tmp"  # per-process name: concurrent first
    # builds (multi-rank launch) must not interleave writes to one file
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread", *srcs, "-o", tmp, "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:
        return None
    os.replace(tmp, out)
    return out


def _load():
    global _lib, AVAILABLE
    path = _build()
    if path is None:
        return
    lib = ctypes.CDLL(path)
    c = ctypes
    lib.pts_server_start.restype = c.c_void_p
    lib.pts_server_start.argtypes = [c.c_int, c.POINTER(c.c_int)]
    lib.pts_server_stop.argtypes = [c.c_void_p]
    lib.pts_client_connect.restype = c.c_void_p
    lib.pts_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pts_client_close.argtypes = [c.c_void_p]
    lib.pts_set.restype = c.c_int
    lib.pts_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_uint32]
    lib.pts_get.restype = c.c_int64
    lib.pts_get.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_uint32, c.c_int64]
    lib.pts_add.restype = c.c_int64
    lib.pts_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]

    lib.ptr_ring_create.restype = c.c_void_p
    lib.ptr_ring_create.argtypes = [c.c_char_p, c.c_uint64]
    lib.ptr_ring_attach.restype = c.c_void_p
    lib.ptr_ring_attach.argtypes = [c.c_char_p]
    lib.ptr_ring_push.restype = c.c_int
    lib.ptr_ring_push.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int]
    lib.ptr_ring_pop.restype = c.c_int64
    lib.ptr_ring_pop.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int]
    lib.ptr_ring_next_size.restype = c.c_uint64
    lib.ptr_ring_next_size.argtypes = [c.c_void_p]
    lib.ptr_ring_close.argtypes = [c.c_void_p]
    lib.ptr_ring_destroy.argtypes = [c.c_void_p]

    lib.phe_create.restype = c.c_void_p
    lib.phe_destroy.argtypes = [c.c_void_p]
    lib.phe_now_ns.restype = c.c_uint64
    lib.phe_intern.restype = c.c_uint32
    lib.phe_intern.argtypes = [c.c_void_p, c.c_char_p]
    lib.phe_record.argtypes = [c.c_void_p, c.c_uint32, c.c_uint64, c.c_uint64, c.c_uint64]
    lib.phe_count.restype = c.c_uint64
    lib.phe_count.argtypes = [c.c_void_p]
    lib.phe_dump.restype = c.c_uint64
    lib.phe_dump.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_uint32),
        c.POINTER(c.c_uint64),
        c.POINTER(c.c_uint64),
        c.POINTER(c.c_uint64),
        c.c_uint64,
        c.c_int,
    ]
    lib.phe_name.restype = c.c_uint32
    lib.phe_name.argtypes = [c.c_void_p, c.c_uint32, c.c_char_p, c.c_uint32]
    _lib = lib
    AVAILABLE = True


_load()


def _retry_until(deadline, attempt_fn, fail_msg, base_s=0.02, cap_s=0.5):
    """Run `attempt_fn` until it returns a truthy handle or `deadline`
    (time.monotonic seconds) passes, sleeping capped-exponential-backoff
    with jitter between attempts.  Startup races — a worker outracing the
    server's bind, or a ring consumer attaching before the producer's
    shm_open — are ordinary under load, so first-refusal failure is the
    wrong contract for constructors; a deadline is."""
    delay = base_s
    while True:
        h = attempt_fn()
        if h:
            return h
        if time.monotonic() >= deadline:
            raise ConnectionError(fail_msg)
        # full jitter: concurrent workers spread their retries instead of
        # stampeding the just-started server in lockstep
        time.sleep(random.uniform(0, min(delay, cap_s)))
        delay *= 2


class TCPStoreServer:
    def __init__(self, port=0):
        p = ctypes.c_int(0)
        self._h = _lib.pts_server_start(port, ctypes.byref(p))
        if not self._h:
            raise OSError(f"TCPStore server failed to bind port {port}")
        self.port = p.value

    def stop(self):
        if self._h:
            _lib.pts_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStoreClient:
    """Reference TCPStore client API: set/get/add/wait (tcp_store.h:121)."""

    def __init__(self, host="127.0.0.1", port=0, timeout_ms=30000):
        # Retry with backoff until timeout_ms instead of failing on the
        # first refusal: each attempt uses a FRESH socket (a connect() that
        # failed can leave the fd in an unusable state, so retrying inside
        # one pts_client_connect call is weaker than reconnecting), with a
        # short per-attempt timeout so the deadline stays shared.
        deadline = time.monotonic() + timeout_ms / 1000.0
        attempt_ms = max(1, min(200, int(timeout_ms)))
        self._h = _retry_until(
            deadline,
            lambda: _lib.pts_client_connect(host.encode(), port, attempt_ms),
            f"cannot reach TCPStore at {host}:{port} "
            f"within {timeout_ms}ms")
        self._lock = threading.Lock()

    def set(self, key: str, value: bytes):
        if _lib.pts_set(self._h, key.encode(), value, len(value)) != 0:
            raise OSError("TCPStore set failed")

    def get(self, key: str, timeout_ms=30000) -> bytes:
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n = _lib.pts_get(self._h, key.encode(), buf, cap, timeout_ms)
        if n == -2:
            raise TimeoutError(f"TCPStore get('{key}') timed out")
        if n < 0:
            raise OSError("TCPStore get failed")
        if n > cap:
            buf = ctypes.create_string_buffer(int(n))
            n = _lib.pts_get(self._h, key.encode(), buf, int(n), timeout_ms)
        return buf.raw[: int(n)]

    def add(self, key: str, delta: int) -> int:
        v = _lib.pts_add(self._h, key.encode(), delta)
        if v == -(2**63):
            raise OSError("TCPStore add failed")
        return int(v)

    def wait(self, keys, timeout_ms=30000):
        """Block until EVERY key exists, under ONE shared deadline.

        `timeout_ms` bounds the whole call, not each key: each get() is
        given only the remaining budget, and an exhausted budget raises
        TimeoutError immediately (the server treats a non-positive
        timeout as wait-forever, so it must never be forwarded)."""
        deadline = time.monotonic() + timeout_ms / 1000.0
        for k in keys if isinstance(keys, (list, tuple)) else [keys]:
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            if remaining_ms <= 0:
                raise TimeoutError(
                    f"TCPStore wait timed out after {timeout_ms}ms with "
                    f"key '{k}' (and possibly later ones) still unset")
            self.get(k, remaining_ms)

    def close(self):
        if self._h:
            _lib.pts_client_close(self._h)
            self._h = None


class ShmRing:
    def __init__(self, name: str, capacity: int = 64 << 20, create=True,
                 attach_timeout_ms: int = 0):
        """attach_timeout_ms (attach side only): retry a failed attach
        with capped exponential backoff until the deadline — a consumer
        process routinely outraces the producer's shm_open under load.
        0 keeps the historical fail-on-first-refusal behavior."""
        self.name = name
        if create:
            self._h = _lib.ptr_ring_create(name.encode(), capacity)
        elif attach_timeout_ms > 0:
            deadline = time.monotonic() + attach_timeout_ms / 1000.0
            self._h = _retry_until(
                deadline,
                lambda: _lib.ptr_ring_attach(name.encode()),
                f"shm ring attach failed: {name} "
                f"(not created within {attach_timeout_ms}ms)")
        else:
            self._h = _lib.ptr_ring_attach(name.encode())
        if not self._h:
            raise OSError(f"shm ring {'create' if create else 'attach'} failed: {name}")

    def push(self, data: bytes, timeout_ms=-1):
        rc = _lib.ptr_ring_push(self._h, data, len(data), timeout_ms)
        if rc == -1:
            raise BrokenPipeError("ring closed")
        if rc == -2:
            raise TimeoutError("ring push timed out")
        if rc == -3:
            raise ValueError("item larger than ring capacity")
        if rc == -5:
            raise BrokenPipeError("ring poisoned (a peer died mid-operation)")

    def pop(self, timeout_ms=-1) -> bytes | None:
        size = _lib.ptr_ring_next_size(self._h)
        cap = max(int(size), 1 << 16)
        buf = ctypes.create_string_buffer(cap)
        n = _lib.ptr_ring_pop(self._h, buf, cap, timeout_ms)
        while n == -4:  # buffer too small; header not consumed — re-query size
            cap = max(int(_lib.ptr_ring_next_size(self._h)), cap * 2)
            buf = ctypes.create_string_buffer(cap)
            n = _lib.ptr_ring_pop(self._h, buf, cap, timeout_ms)
        if n == -2:
            raise TimeoutError("ring pop timed out")
        if n == -5:
            raise BrokenPipeError("ring poisoned (a peer died mid-operation)")
        if n == 0:
            return None  # closed and drained
        return buf.raw[: int(n)]

    def close(self):
        _lib.ptr_ring_close(self._h)

    def destroy(self):
        if self._h:
            _lib.ptr_ring_destroy(self._h)
            self._h = None


class HostEventRecorder:
    def __init__(self):
        self._h = _lib.phe_create()
        self._names = {}

    def intern(self, name: str) -> int:
        nid = self._names.get(name)
        if nid is None:
            nid = _lib.phe_intern(self._h, name.encode())
            self._names[name] = nid
        return nid

    def now_ns(self) -> int:
        return int(_lib.phe_now_ns())

    def record(self, name_id: int, start_ns: int, end_ns: int, tid: int = 0):
        _lib.phe_record(self._h, name_id, start_ns, end_ns, tid)

    def dump(self, clear=True):
        import numpy as np

        n = int(_lib.phe_count(self._h))
        if n == 0:
            return []
        ids = np.zeros(n, np.uint32)
        st = np.zeros(n, np.uint64)
        en = np.zeros(n, np.uint64)
        tid = np.zeros(n, np.uint64)
        got = int(
            _lib.phe_dump(
                self._h,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                st.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                en.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                tid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                n,
                1 if clear else 0,
            )
        )
        rev = {v: k for k, v in self._names.items()}
        out = []
        for i in range(got):
            name = rev.get(int(ids[i]))
            if name is None:
                buf = ctypes.create_string_buffer(256)
                ln = _lib.phe_name(self._h, int(ids[i]), buf, 256)
                name = buf.raw[:ln].decode()
            out.append((name, int(st[i]), int(en[i]), int(tid[i])))
        return out

    def __del__(self):
        try:
            if self._h:
                _lib.phe_destroy(self._h)
        except Exception:
            pass
