// Shared-memory ring buffer — DataLoader worker transport.
//
// Capability parity with the reference's shared-memory dataloader queues
// (python/paddle/io/dataloader/dataloader_iter.py multi-process workers +
// paddle/fluid/memory shared storage): worker processes push serialized
// sample batches into a POSIX shm ring; the trainer process pops them
// without a pickle-through-pipe round trip.  Process-shared pthread
// mutex/condvars in the shm header give blocking push/pop with backpressure.

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;   // data bytes
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes used
  uint32_t n_items;
  uint32_t closed;
  uint32_t poisoned;
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  uint64_t cap;
  std::string name;
  bool owner;
};

// item framing: u64 length then payload (wrapping)
void ring_write(Ring* r, const uint8_t* src, uint64_t n) {
  uint64_t tail = r->hdr->tail;
  uint64_t first = std::min(n, r->cap - tail);
  memcpy(r->data + tail, src, first);
  if (n > first) memcpy(r->data, src + first, n - first);
  r->hdr->tail = (tail + n) % r->cap;
  r->hdr->used += n;
}

void ring_read(Ring* r, uint8_t* dst, uint64_t n) {
  uint64_t head = r->hdr->head;
  uint64_t first = std::min(n, r->cap - head);
  memcpy(dst, r->data + head, first);
  if (n > first) memcpy(dst + first, r->data, n - first);
  r->hdr->head = (head + n) % r->cap;
  r->hdr->used -= n;
}

// read the next item's length header without advancing head
uint64_t ring_peek_len(Ring* r) {
  uint64_t head = r->hdr->head;
  uint8_t buf[8];
  uint64_t first = std::min<uint64_t>(8, r->cap - head);
  memcpy(buf, r->data + head, first);
  if (8 > first) memcpy(buf + first, r->data, 8 - first);
  uint64_t len;
  memcpy(&len, buf, 8);
  return len;
}

}  // namespace

extern "C" {

void* ptr_ring_create(const char* name, uint64_t capacity) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + capacity;
  if (ftruncate(fd, total) != 0) {
    ::close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<Header*>(mem);
  memset(hdr, 0, sizeof(Header));
  hdr->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);

  auto* r = new Ring{hdr, reinterpret_cast<uint8_t*>(hdr + 1), capacity, name, true};
  return r;
}

void* ptr_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  fstat(fd, &st);
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<Header*>(mem);
  auto* r = new Ring{hdr, reinterpret_cast<uint8_t*>(hdr + 1), hdr->capacity, name, false};
  return r;
}

static int lock_robust(Header* hdr) {
  int rc = pthread_mutex_lock(&hdr->mu);
  if (rc == EOWNERDEAD) {  // a worker died holding the lock: ring state
    // (head/tail/used/n_items) may be mid-update and the item framing
    // unrecoverable — poison by closing so both sides fail loudly instead
    // of reading garbage lengths
    pthread_mutex_consistent(&hdr->mu);
    hdr->closed = 1;
    hdr->poisoned = 1;
    pthread_cond_broadcast(&hdr->not_empty);
    pthread_cond_broadcast(&hdr->not_full);
    return 0;
  }
  return rc;
}

// returns 0 ok, -1 closed, -2 timeout, -3 item larger than capacity, -5 poisoned
int ptr_ring_push(void* h, const uint8_t* data, uint64_t len, int timeout_ms) {
  auto* r = static_cast<Ring*>(h);
  Header* hdr = r->hdr;
  uint64_t need = len + 8;
  if (need > r->cap) return -3;
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
  if (lock_robust(hdr) != 0) return -1;
  if (hdr->poisoned) {
    pthread_mutex_unlock(&hdr->mu);
    return -5;
  }
  while (hdr->capacity - hdr->used < need && !hdr->closed) {
    if (timeout_ms >= 0) {
      if (pthread_cond_timedwait(&hdr->not_full, &hdr->mu, &ts) == ETIMEDOUT) {
        pthread_mutex_unlock(&hdr->mu);
        return -2;
      }
    } else {
      pthread_cond_wait(&hdr->not_full, &hdr->mu);
    }
  }
  if (hdr->closed) {
    pthread_mutex_unlock(&hdr->mu);
    return -1;
  }
  uint64_t len64 = len;
  ring_write(r, reinterpret_cast<uint8_t*>(&len64), 8);
  ring_write(r, data, len);
  hdr->n_items++;
  pthread_cond_signal(&hdr->not_empty);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

// returns item length, 0 if none & closed, -2 timeout, -4 cap too small, -5 poisoned
int64_t ptr_ring_pop(void* h, uint8_t* out, uint64_t cap, int timeout_ms) {
  auto* r = static_cast<Ring*>(h);
  Header* hdr = r->hdr;
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
  if (lock_robust(hdr) != 0) return 0;
  if (hdr->poisoned) {
    pthread_mutex_unlock(&hdr->mu);
    return -5;
  }
  while (hdr->n_items == 0) {
    if (hdr->closed) {
      pthread_mutex_unlock(&hdr->mu);
      return 0;
    }
    if (timeout_ms >= 0) {
      if (pthread_cond_timedwait(&hdr->not_empty, &hdr->mu, &ts) == ETIMEDOUT) {
        pthread_mutex_unlock(&hdr->mu);
        return -2;
      }
    } else {
      pthread_cond_wait(&hdr->not_empty, &hdr->mu);
    }
  }
  uint64_t len = ring_peek_len(r);
  if (len > cap) {  // caller buffer too small: header NOT consumed, caller
    pthread_mutex_unlock(&hdr->mu);  // re-queries next_size and retries
    return -4;
  }
  uint64_t skip;
  ring_read(r, reinterpret_cast<uint8_t*>(&skip), 8);
  ring_read(r, out, len);
  hdr->n_items--;
  pthread_cond_signal(&hdr->not_full);
  pthread_mutex_unlock(&hdr->mu);
  return static_cast<int64_t>(len);
}

// peek next item's size (0 if empty)
uint64_t ptr_ring_next_size(void* h) {
  auto* r = static_cast<Ring*>(h);
  if (lock_robust(r->hdr) != 0) return 0;
  uint64_t len = 0;
  if (r->hdr->n_items > 0) len = ring_peek_len(r);
  pthread_mutex_unlock(&r->hdr->mu);
  return len;
}

void ptr_ring_close(void* h) {
  auto* r = static_cast<Ring*>(h);
  lock_robust(r->hdr);
  r->hdr->closed = 1;
  pthread_cond_broadcast(&r->hdr->not_empty);
  pthread_cond_broadcast(&r->hdr->not_full);
  pthread_mutex_unlock(&r->hdr->mu);
}

void ptr_ring_destroy(void* h) {
  auto* r = static_cast<Ring*>(h);
  uint64_t total = sizeof(Header) + r->cap;
  bool owner = r->owner;
  std::string name = r->name;
  munmap(r->hdr, total);
  if (owner) shm_unlink(name.c_str());
  delete r;
}

}  // extern "C"
