// Host event recorder — low-overhead profiler spans.
//
// Capability parity with the reference's HostEventRecorder
// (paddle/fluid/platform/profiler/host_event_recorder.h: thread-local
// chunked event buffers merged at collection).  One lock-free-per-thread
// design is overkill for the Python-driven funnel, so this keeps a
// mutex-guarded growable buffer of {name_id, start_ns, end_ns, tid} with an
// interned name table; ~100ns per record vs ~1us for the Python path.

#include <cstdint>
#include <cstring>
#include <ctime>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  uint32_t name_id;
  uint64_t start_ns;
  uint64_t end_ns;
  uint64_t tid;
};

struct Recorder {
  std::mutex mu;
  std::vector<Event> events;
  std::vector<std::string> names;
  std::map<std::string, uint32_t> name_ids;
};

uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000ULL + ts.tv_nsec;
}

}  // namespace

extern "C" {

void* phe_create() { return new Recorder(); }

void phe_destroy(void* h) { delete static_cast<Recorder*>(h); }

uint64_t phe_now_ns() { return now_ns(); }

uint32_t phe_intern(void* h, const char* name) {
  auto* r = static_cast<Recorder*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->name_ids.find(name);
  if (it != r->name_ids.end()) return it->second;
  uint32_t id = static_cast<uint32_t>(r->names.size());
  r->names.emplace_back(name);
  r->name_ids[name] = id;
  return id;
}

void phe_record(void* h, uint32_t name_id, uint64_t start_ns, uint64_t end_ns, uint64_t tid) {
  auto* r = static_cast<Recorder*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  r->events.push_back({name_id, start_ns, end_ns, tid});
}

uint64_t phe_count(void* h) {
  auto* r = static_cast<Recorder*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  return r->events.size();
}

// dump into caller arrays (each of length >= count); returns copied count
uint64_t phe_dump(void* h, uint32_t* name_ids, uint64_t* starts, uint64_t* ends,
                  uint64_t* tids, uint64_t cap, int clear) {
  auto* r = static_cast<Recorder*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  uint64_t n = r->events.size() < cap ? r->events.size() : cap;
  for (uint64_t i = 0; i < n; ++i) {
    name_ids[i] = r->events[i].name_id;
    starts[i] = r->events[i].start_ns;
    ends[i] = r->events[i].end_ns;
    tids[i] = r->events[i].tid;
  }
  if (clear) r->events.clear();
  return n;
}

// name table lookup: copies name `id` into buf, returns its length
uint32_t phe_name(void* h, uint32_t id, char* buf, uint32_t cap) {
  auto* r = static_cast<Recorder*>(h);
  std::lock_guard<std::mutex> g(r->mu);
  if (id >= r->names.size()) return 0;
  const std::string& s = r->names[id];
  uint32_t n = static_cast<uint32_t>(s.size()) < cap ? s.size() : cap;
  memcpy(buf, s.data(), n);
  return static_cast<uint32_t>(s.size());
}

}  // extern "C"
