// TCPStore — rendezvous key-value store.
//
// Capability parity with the reference's bootstrap store
// (paddle/phi/core/distributed/store/tcp_store.h:121, socket.cpp):
// rank0 hosts a tiny TCP server; all ranks SET/GET/ADD/WAIT keys to
// exchange addresses and barrier before collective init.  Redesigned (not
// translated): single poll()-driven server thread, length-prefixed binary
// protocol, blocking GET with deadline implemented server-side via deferred
// replies (no client polling).
//
// C ABI (ctypes): pts_store_* functions at the bottom.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>
#include <algorithm>
#include <atomic>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kDelete = 5 };

struct Pending {  // a blocked GET/WAIT
  int fd;
  std::string key;
  int64_t deadline_ms;
};

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= w;
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

bool send_blob(int fd, const std::string& v) {
  uint32_t len = static_cast<uint32_t>(v.size());
  return send_all(fd, &len, 4) && (len == 0 || send_all(fd, v.data(), len));
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::thread thr;
  std::atomic<bool> stop{false};
  std::map<std::string, std::string> kv;
  std::vector<Pending> pending;
  std::mutex mu;

  void flush_pending() {
    int64_t now = now_ms();
    for (auto it = pending.begin(); it != pending.end();) {
      auto kvit = kv.find(it->key);
      if (kvit != kv.end()) {
        send_blob(it->fd, kvit->second);
        it = pending.erase(it);
      } else if (it->deadline_ms > 0 && now > it->deadline_ms) {
        uint32_t timeout_marker = 0xFFFFFFFFu;
        send_all(it->fd, &timeout_marker, 4);
        it = pending.erase(it);
      } else {
        ++it;
      }
    }
  }

  // one request per poll wakeup per client; clients are ranks (few dozens)
  bool handle(int fd) {
    uint8_t cmd;
    if (!recv_all(fd, &cmd, 1)) return false;
    uint32_t klen;
    if (!recv_all(fd, &klen, 4) || klen > 1 << 20) return false;
    std::string key(klen, 0);
    if (klen && !recv_all(fd, &key[0], klen)) return false;

    switch (cmd) {
      case kSet: {
        uint32_t vlen;
        if (!recv_all(fd, &vlen, 4) || vlen > 1u << 30) return false;
        std::string val(vlen, 0);
        if (vlen && !recv_all(fd, &val[0], vlen)) return false;
        {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = std::move(val);
        }
        uint8_t ok = 1;
        return send_all(fd, &ok, 1);
      }
      case kGet: {
        int64_t timeout_ms;
        if (!recv_all(fd, &timeout_ms, 8)) return false;
        std::lock_guard<std::mutex> g(mu);
        auto it = kv.find(key);
        if (it != kv.end()) return send_blob(fd, it->second);
        pending.push_back({fd, key, timeout_ms > 0 ? now_ms() + timeout_ms : 0});
        return true;
      }
      case kAdd: {
        int64_t delta;
        if (!recv_all(fd, &delta, 8)) return false;
        int64_t cur = 0;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string v(8, 0);
          memcpy(&v[0], &cur, 8);
          kv[key] = v;
        }
        return send_all(fd, &cur, 8);
      }
      case kDelete: {
        std::lock_guard<std::mutex> g(mu);
        kv.erase(key);
        uint8_t ok = 1;
        return send_all(fd, &ok, 1);
      }
      default:
        return false;
    }
  }

  void run() {
    std::vector<int> clients;
    while (!stop) {
      std::vector<pollfd> fds;
      fds.push_back({listen_fd, POLLIN, 0});
      for (int c : clients) fds.push_back({c, POLLIN, 0});
      int rc = ::poll(fds.data(), fds.size(), 50);
      if (rc < 0) continue;
      if (fds[0].revents & POLLIN) {
        int c = ::accept(listen_fd, nullptr, nullptr);
        if (c >= 0) {
          int one = 1;
          setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          clients.push_back(c);
        }
      }
      for (size_t i = 1; i < fds.size(); ++i) {
        if (fds[i].revents & (POLLIN | POLLHUP | POLLERR)) {
          if (!handle(fds[i].fd)) {
            // purge pending GETs for this fd before the number can be reused
            // by a future accept(), else the deferred reply would be written
            // into an unrelated client's stream
            {
              std::lock_guard<std::mutex> g(mu);
              int dead = fds[i].fd;
              pending.erase(
                  std::remove_if(pending.begin(), pending.end(),
                                 [dead](const Pending& p) { return p.fd == dead; }),
                  pending.end());
            }
            ::close(fds[i].fd);
            clients.erase(std::find(clients.begin(), clients.end(), fds[i].fd));
          }
        }
      }
      std::lock_guard<std::mutex> g(mu);
      flush_pending();
    }
    for (int c : clients) ::close(c);
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;
};

}  // namespace

extern "C" {

void* pts_server_start(int port, int* out_port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(port);
  if (::bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &len);
  s->port = ntohs(addr.sin_port);
  if (out_port) *out_port = s->port;
  s->thr = std::thread([s] { s->run(); });
  return s;
}

void pts_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop = true;
  s->thr.join();
  ::close(s->listen_fd);
  delete s;
}

void* pts_client_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, host, &addr.sin_addr);
  int64_t deadline = now_ms() + timeout_ms;
  while (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    if (now_ms() > deadline) {
      ::close(fd);
      return nullptr;
    }
    usleep(50 * 1000);
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void pts_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

int pts_set(void* h, const char* key, const uint8_t* val, uint32_t len) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t cmd = kSet;
  uint32_t klen = strlen(key);
  if (!send_all(c->fd, &cmd, 1) || !send_all(c->fd, &klen, 4) ||
      !send_all(c->fd, key, klen) || !send_all(c->fd, &len, 4) ||
      (len && !send_all(c->fd, val, len)))
    return -1;
  uint8_t ok;
  return recv_all(c->fd, &ok, 1) ? 0 : -1;
}

// returns value length, or -1 on error, -2 on timeout. caller passes cap.
int64_t pts_get(void* h, const char* key, uint8_t* out, uint32_t cap, int64_t timeout_ms) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t cmd = kGet;
  uint32_t klen = strlen(key);
  if (!send_all(c->fd, &cmd, 1) || !send_all(c->fd, &klen, 4) ||
      !send_all(c->fd, key, klen) || !send_all(c->fd, &timeout_ms, 8))
    return -1;
  uint32_t vlen;
  if (!recv_all(c->fd, &vlen, 4)) return -1;
  if (vlen == 0xFFFFFFFFu) return -2;
  std::vector<uint8_t> tmp(vlen);
  if (vlen && !recv_all(c->fd, tmp.data(), vlen)) return -1;
  memcpy(out, tmp.data(), vlen < cap ? vlen : cap);
  return vlen;
}

int64_t pts_add(void* h, const char* key, int64_t delta) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t cmd = kAdd;
  uint32_t klen = strlen(key);
  if (!send_all(c->fd, &cmd, 1) || !send_all(c->fd, &klen, 4) ||
      !send_all(c->fd, key, klen) || !send_all(c->fd, &delta, 8))
    return INT64_MIN;
  int64_t v;
  return recv_all(c->fd, &v, 8) ? v : INT64_MIN;
}

}  // extern "C"
