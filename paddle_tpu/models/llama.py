"""LLaMA-family decoder (flagship model).

Capability target: the reference trains LLaMA-2 via PaddleNLP on fleet hybrid
parallel (BASELINE.md north star).  Architecture built on this framework's nn
API; TPU-first choices:
- bfloat16 parameters/activations by default, fp32 RMSNorm statistics;
- rotary embeddings computed once and gathered (no per-step trig);
- attention via scaled_dot_product_attention → XLA fused attention or the
  Pallas flash kernel;
- shapes chosen MXU-friendly (head_dim multiple of 128 recommended at scale).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.tensor._ops_common import apply

__all__ = [
    "LlamaConfig",
    "LlamaForCausalLM",
    "LlamaModel",
    "LlamaDecoderLayer",
    "shard_llama",
    "LLAMA_TP_COL_TARGETS",
    "LLAMA_TP_ROW_TARGETS",
    "pipeline_llama",
    "context_parallel_llama",
    "prefill_chain_scope",
    "llama_tiny",
    "llama_7b",
]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # parallel hints consumed by the distributed layer (tp/sp shardings)
    tensor_parallel_degree: int = 1
    sequence_parallel: bool = False
    use_recompute: bool = False
    # recompute tier inside each block (reference recompute_granularity):
    # "full" | "full_attn" | "core_attn"
    recompute_granularity: str = "full"
    # run the decoder stack as ONE jax.lax.scan over stacked per-layer
    # weights (nn.LayerStack): trace/compile cost becomes O(1) in depth.
    # FLAGS_scan_layers forces this on for every model built afterwards.
    fuse_layer_stack: bool = False


def _rope_tables(head_dim: int, max_len: int, theta: float):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_len, head_dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_rotate(qv, kv, c_t, s_t):
    """Rotate-half on [B, S, N, H] given pre-sliced cos/sin [S, H/2]."""
    c_t = c_t[None, :, None, :]
    s_t = s_t[None, :, None, :]

    def rot(x):
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        xr1 = x1 * c_t - x2 * s_t
        xr2 = x2 * c_t + x1 * s_t
        return jnp.stack([xr1, xr2], axis=-1).reshape(x.shape)

    return rot(qv).astype(qv.dtype), rot(kv).astype(kv.dtype)


def apply_rotary_pos_emb(q, k, cos, sin, position_offset=0):
    """Rotate half formulation on [B, S, N, H] tensors (reference fused_rope
    kernel paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu — here one
    fused XLA elementwise chain; a Pallas variant lives in paddle_tpu.ops).

    position_offset may be a Tensor (traced — e.g. a sequence-parallel
    rank's shard offset); the table slice then lowers to dynamic_slice."""
    from paddle_tpu._core.tensor import Tensor as _T

    if isinstance(position_offset, _T):
        def _rope_dyn(qv, kv, c, s, off):
            import jax.lax as _lax

            S = qv.shape[1]
            c_t = _lax.dynamic_slice_in_dim(c, off, S, 0)
            s_t = _lax.dynamic_slice_in_dim(s, off, S, 0)
            return _rope_rotate(qv, kv, c_t, s_t)

        return apply("rotary_pos_emb", _rope_dyn, q, k, cos, sin, position_offset)

    def _rope(qv, kv, c, s):
        S = qv.shape[1]
        return _rope_rotate(
            qv, kv,
            c[position_offset : position_offset + S],
            s[position_offset : position_offset + S],
        )

    return apply("rotary_pos_emb", _rope, q, k, cos, sin)


# Accepted prefill-attention schedule (schedule search; PrefillChainSpec)
# for the chunked-prefill scope the engine is currently inside, or None.
# A module global, not engine state: LlamaAttention.forward is the one
# place that knows whether THIS call is the eligible prefill core.
_PREFILL_CHAIN_CFG = None


def prefill_chain_scope(cfg):
    """Scope an accepted prefill-chain config over a chunked prefill
    (serving._try_admit): inside the scope every eligible
    LlamaAttention.forward prefill core — batch 1, multi-token chunk, no
    explicit mask, no context parallelism, shapes the config tiles —
    runs as ONE fused K-tiled Pallas dispatch (ops.decode_chain.
    fused_prefill_attention) instead of the XLA einsum chain; everything
    else keeps the XLA path.  cfg=None is a no-op scope."""
    import contextlib

    @contextlib.contextmanager
    def _ctx():
        global _PREFILL_CHAIN_CFG
        prev = _PREFILL_CHAIN_CFG
        _PREFILL_CHAIN_CFG = cfg
        try:
            yield
        finally:
            _PREFILL_CHAIN_CFG = prev

    return _ctx()


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        bias = False
        self.q_proj = nn.Linear(self.hidden_size, self.num_heads * self.head_dim, bias_attr=bias)
        self.k_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim, bias_attr=bias)
        self.v_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim, bias_attr=bias)
        self.o_proj = nn.Linear(self.num_heads * self.head_dim, self.hidden_size, bias_attr=bias)

    def forward(self, hidden_states, rope_cos, rope_sin, attn_mask=None, kv_cache=None, position_offset=0):
        b, s, _ = hidden_states.shape
        q = self.q_proj(hidden_states).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(hidden_states).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(hidden_states).reshape([b, s, self.num_kv_heads, self.head_dim])
        sep_ax = None
        if getattr(self, "_sep_mode", None):
            # one gate for BOTH the rope offset and the attention branch:
            # rope offsets and ring exchange must engage together
            from paddle_tpu.distributed.communication import current_axis_scope

            ax = current_axis_scope().get("sep")
            if ax is not None and (attn_mask is not None or kv_cache is not None):
                # silently skipping the sep path would make each rank compute
                # plain local attention with offset-0 rope -> wrong logits
                raise ValueError(
                    "context-parallel ('sep') attention supports neither "
                    "attn_mask nor kv_cache: drop them inside the sep axis "
                    "scope, or run this layer without context parallelism"
                )
            sep_ax = ax
        if sep_ax is not None:
            # sequence sharded over 'sep': this shard's tokens sit at global
            # positions rank*s .. rank*s + s, so the rope tables must be
            # sliced at the rank offset (dynamic under tracing)
            import jax.lax as _lax

            rope_len = int(rope_cos.shape[0])

            def _sep_off(z, ax=sep_ax, s=s, rope_len=rope_len):
                from paddle_tpu.distributed.shard_map_compat import axis_size

                w = axis_size(ax)
                if s * w > rope_len:
                    raise ValueError(
                        f"context parallelism: global sequence {s * w} "
                        f"exceeds the rope table ({rope_len} positions); "
                        "raise max_position_embeddings"
                    )
                return (z + _lax.axis_index(ax) * s).astype(jnp.int32)

            base = (
                position_offset
                if isinstance(position_offset, Tensor)
                else paddle.full([], int(position_offset), "int32")
            )
            position_offset = apply("sep_pos_offset", _sep_off, base)
        q, k = apply_rotary_pos_emb(q, k, rope_cos, rope_sin, position_offset)
        if kv_cache is not None:
            k = paddle.concat([kv_cache[0], k], axis=1)
            v = paddle.concat([kv_cache[1], v], axis=1)
            new_cache = (k, v)
        else:
            new_cache = None
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = paddle.repeat_interleave(k, rep, axis=2)
            v = paddle.repeat_interleave(v, rep, axis=2)
        # multi-token chunk on a non-empty cache (chunked prefill /
        # speculative verify) is safe: both attention paths are
        # bottom-right aligned for Sq != Sk, so chunk token i attends to
        # the cache plus chunk positions <= i
        if sep_ax is not None:
            # context parallelism (context_parallel_llama): the sequence is
            # sharded over the 'sep' axis — ring/Ulysses attention exchange
            # K/V shards over ICI instead of materializing the full sequence
            from paddle_tpu.distributed.fleet.meta_parallel.segment_parallel import (
                sep_attention,
            )

            out = sep_attention(q, k, v, causal=True, mode=self._sep_mode)
        else:
            chain = _PREFILL_CHAIN_CFG
            bq = int(chain.get("block_q", 0)) if chain else 0
            kch = int(chain.get("kchunk", 1) or 1) if chain else 1
            if (chain is not None and attn_mask is None and s > 1
                    and b == 1 and bq >= 2 and s % bq == 0
                    and int(k.shape[1]) % kch == 0):
                # fused chunked-prefill attention core (prefill_chain_scope;
                # the accepted schedule tiles this chunk exactly) — the
                # config rides kwargs so the dispatch cache keys on it
                from paddle_tpu.ops import decode_chain as _dc

                def _fused_prefill(qv, kv_, vv, *, block_q, stage, kchunk):
                    return _dc.fused_prefill_attention(
                        qv, kv_, vv, block_q=block_q, stage=stage,
                        kchunk=kchunk)

                out = apply("fused_prefill_attention", _fused_prefill,
                            q, k, v,
                            block_q=int(chain["block_q"]),
                            stage=chain.get("stage", "take"),
                            kchunk=int(chain.get("kchunk", 1) or 1))
            else:
                # empty-cache prefill is causal; a cached single-token
                # decode attends to everything it has
                out = F.scaled_dot_product_attention(
                    q, k, v, attn_mask=attn_mask,
                    is_causal=(kv_cache is None) or s > 1
                )
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if new_cache is not None:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    """SwiGLU MLP — gate/up fused into one matmul (MXU-friendly)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.gate_up_proj = nn.Linear(config.hidden_size, 2 * config.intermediate_size, bias_attr=False)
        self.down_proj = nn.Linear(config.intermediate_size, config.hidden_size, bias_attr=False)
        self.intermediate_size = config.intermediate_size

    def forward(self, x):
        gate_up = self.gate_up_proj(x)
        gate, up = paddle.split(gate_up, 2, axis=-1)
        from paddle_tpu import ops as _ops

        if _ops.use_pallas():
            import paddle_tpu.incubate.nn.functional as _FF

            return self.down_proj(_FF.swiglu(gate, up))
        return self.down_proj(F.silu(gate) * up)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self._use_recompute = config.use_recompute

    def forward(self, hidden_states, rope_cos, rope_sin, attn_mask=None, kv_cache=None, position_offset=0):
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        new_cache = None
        if kv_cache is not None:
            h, new_cache = self.self_attn(
                h, rope_cos, rope_sin, attn_mask, kv_cache=kv_cache, position_offset=position_offset
            )
        else:
            from paddle_tpu.nn.layer.stack import current_recompute_tier

            if current_recompute_tier() == "full_attn":
                # recompute_granularity="full_attn": exactly the attention
                # sublayer rematerializes in backward (nested jax.checkpoint
                # via fleet.recompute); MLP/norm residuals stay saved
                from paddle_tpu.distributed.fleet.recompute import recompute

                h = recompute(self.self_attn, h, rope_cos, rope_sin, attn_mask)
            else:
                h = self.self_attn(h, rope_cos, rope_sin, attn_mask)
        h = residual + h
        residual = h
        h2 = self.post_attention_layernorm(h)
        h2 = self.mlp(h2)
        out = residual + h2
        if new_cache is not None:
            return out, new_cache
        return out


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        from paddle_tpu._core import flags as _flags

        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        blocks = [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        if config.fuse_layer_stack or _flags.flag("FLAGS_scan_layers"):
            # one scanned block instead of N unrolled ones: trace + XLA
            # compile cost is O(1) in depth (docs/SCAN_LAYERS.md)
            self.layers = nn.LayerStack(
                blocks,
                recompute=(config.recompute_granularity
                           if config.use_recompute else None),
                needs_rng=False,  # no stochastic sublayers in the block
            )
        else:
            self.layers = nn.LayerList(blocks)
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_tables(head_dim, config.max_position_embeddings, config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)
        if config.dtype == "bfloat16":
            self.to(dtype="bfloat16")
            # rope tables stay fp32 for precision
            self.rope_cos._bind(cos)
            self.rope_sin._bind(sin)

    def forward(self, input_ids, attn_mask=None):
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

        if getattr(self, "_pp_full", False):
            # full-model pipeline: embedding rides the first stage and
            # norm+head the last (reference SegmentLayers pp_layers.py:92);
            # the stack consumes token ids and emits logits
            return self.layers(input_ids, self.rope_cos, self.rope_sin, attn_mask)
        h = self.embed_tokens(input_ids)
        if isinstance(self.layers, (PipelineStack, nn.LayerStack)):
            h = self.layers(h, self.rope_cos, self.rope_sin, attn_mask)
        else:
            gran = self.config.recompute_granularity
            for layer in self.layers:
                if self.config.use_recompute and self.training:
                    if gran == "full":
                        from paddle_tpu.distributed.fleet.recompute import recompute

                        h = recompute(layer, h, self.rope_cos, self.rope_sin, attn_mask)
                    else:
                        # sub-layer tiers: the block itself remats its
                        # attention (full_attn) or its attention core
                        # (core_attn) under this scope
                        from paddle_tpu.nn.layer.stack import recompute_tier_scope

                        with recompute_tier_scope(gran):
                            h = layer(h, self.rope_cos, self.rope_sin, attn_mask)
                else:
                    h = layer(h, self.rope_cos, self.rope_sin, attn_mask)
        return self.norm(h)


def _proj_lora(proj, x, ad, name, slots, scaling):
    """A target projection's raw output, plus its gathered per-row LoRA
    delta when the adapter pack covers it (nn/lora.py lora_delta).  x is
    the projection's input Tensor; returns a raw [B, T, out] array."""
    out = proj(x)._value
    if ad is not None and name in ad:
        from paddle_tpu.nn.lora import lora_delta

        out = out + lora_delta(x._value, *ad[name], slots, scaling)
    return out


def _mlp_paged(mlp, x, ad, slots, scaling):
    """layer.mlp(x) with optional LoRA deltas on gate_up/down — mirrors
    LlamaMLP.forward so the no-adapter decode program is unchanged."""
    if ad is None or ("mlp.gate_up_proj" not in ad
                      and "mlp.down_proj" not in ad):
        return mlp(x)
    gate_up = Tensor(_proj_lora(mlp.gate_up_proj, x, ad, "mlp.gate_up_proj",
                                slots, scaling))
    gate, up = paddle.split(gate_up, 2, axis=-1)
    from paddle_tpu import ops as _ops

    if _ops.use_pallas():
        import paddle_tpu.incubate.nn.functional as _FF

        act = _FF.swiglu(gate, up)
    else:
        act = F.silu(gate) * up
    return Tensor(_proj_lora(mlp.down_proj, act, ad, "mlp.down_proj",
                             slots, scaling))


def _decode_layer_paged(layer, h, cos, sin, kc, vc, tables, lens,
                        ad=None, slots=None, scaling=None, chain_cfg=None):
    """One decoder layer on one new token against the paged KV pools.

    h: Tensor [B, 1, D]; kc/vc: [num_blocks, Nkv, bs, H] pools (raw arrays);
    tables: [B, max_blocks]; lens: [B] lengths INCLUDING this token.
    Returns (Tensor h', kc', vc').

    ad/slots/scaling: optional multi-tenant LoRA state — ad maps target
    paths to THIS layer's slot-stacked (A [S, in, r], B [S, r, out]);
    slots [B] picks each batch row's adapter slot and scaling [B] its
    alpha/rank, so mixed-adapter batches decode in this ONE program
    (slot 0 gathers zeros — the exact base-model identity; nn/lora.py).

    chain_cfg: an ACCEPTED decode-chain schedule (ops/decode_chain.py;
    docs/SCHEDULE_SEARCH.md phase 2) — the write→write→attend sequence
    below runs as one fused Pallas dispatch instead of separate XLA ops.
    Only the serving engine passes this, and only after the measured-win
    gate and the stream parity gate said yes.
    """
    from paddle_tpu.ops import paged_attention as pa

    attn = layer.self_attn
    residual = h
    x = layer.input_layernorm(h)
    b = int(x.shape[0])
    n, nkv, hd = attn.num_heads, attn.num_kv_heads, attn.head_dim
    qv = _proj_lora(attn.q_proj, x, ad, "self_attn.q_proj", slots,
                    scaling).reshape(b, n, hd)
    kv_ = _proj_lora(attn.k_proj, x, ad, "self_attn.k_proj", slots,
                     scaling).reshape(b, nkv, hd)
    vv = _proj_lora(attn.v_proj, x, ad, "self_attn.v_proj", slots,
                    scaling).reshape(b, nkv, hd)
    pos = lens - 1
    qv = pa.rope_rotate_by_position(qv, cos, sin, pos)
    kv_ = pa.rope_rotate_by_position(kv_, cos, sin, pos)
    if chain_cfg is not None:
        from paddle_tpu.ops import decode_chain as _dc

        o, kc, vc = _dc.fused_decode_step(kc, vc, qv, kv_, vv, tables,
                                          lens, config=chain_cfg)
    else:
        kc = pa.paged_write(kc, kv_, tables, pos)
        vc = pa.paged_write(vc, vv, tables, pos)
        o = pa.paged_decode_attention(qv, kc, vc, tables, lens)
    out = Tensor(_proj_lora(attn.o_proj, Tensor(o.reshape(b, 1, n * hd)),
                            ad, "self_attn.o_proj", slots, scaling))
    h = residual + out
    residual = h
    h2 = layer.post_attention_layernorm(h)
    h2 = _mlp_paged(layer.mlp, h2, ad, slots, scaling)
    return residual + h2, kc, vc


def _decode_layer_paged_chunk(layer, h, cos, sin, kc, vc, tables, lens,
                              ad=None, slots=None, scaling=None):
    """One decoder layer on a T-token chunk against the paged KV pools
    (speculative verify / chunked paged decode).

    h: Tensor [B, T, D]; lens: [B] lengths INCLUDING all T chunk tokens.
    Chunk token j sits at global position lens - T + j.  Returns
    (Tensor h', kc', vc').  ad/slots/scaling as in _decode_layer_paged."""
    from paddle_tpu.ops import paged_attention as pa

    attn = layer.self_attn
    residual = h
    x = layer.input_layernorm(h)
    b, t = int(x.shape[0]), int(x.shape[1])
    n, nkv, hd = attn.num_heads, attn.num_kv_heads, attn.head_dim
    qv = _proj_lora(attn.q_proj, x, ad, "self_attn.q_proj", slots,
                    scaling).reshape(b, t, n, hd)
    kv_ = _proj_lora(attn.k_proj, x, ad, "self_attn.k_proj", slots,
                     scaling).reshape(b, t, nkv, hd)
    vv = _proj_lora(attn.v_proj, x, ad, "self_attn.v_proj", slots,
                    scaling).reshape(b, t, nkv, hd)
    pos = lens[:, None] - t + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B,T]
    qv = pa.rope_rotate_chunk(qv, cos, sin, pos)
    kv_ = pa.rope_rotate_chunk(kv_, cos, sin, pos)
    kc = pa.paged_write_chunk(kc, kv_, tables, pos)
    vc = pa.paged_write_chunk(vc, vv, tables, pos)
    o = pa.paged_chunk_attention(qv, kc, vc, tables, lens)
    out = Tensor(_proj_lora(attn.o_proj, Tensor(o.reshape(b, t, n * hd)),
                            ad, "self_attn.o_proj", slots, scaling))
    h = residual + out
    residual = h
    h2 = layer.post_attention_layernorm(h)
    h2 = _mlp_paged(layer.mlp, h2, ad, slots, scaling)
    return residual + h2, kc, vc


def _decode_layers_paged(layers, h, cos, sin, kpools, vpools, tables, lens,
                         chunk=False, adapters=None, slots=None,
                         scaling=None, chain_cfg=None):
    """Run every decoder layer's paged decode step over per-layer pools.

    ``layers`` is either a LayerList (unrolled view loop — the program
    traces N layer bodies) or an ``nn.LayerStack`` (the pools stack on a
    leading layer axis INSIDE this trace and thread through ONE
    ``lax.scan`` as per-layer state — trace and XLA compile are O(1) in
    depth, closing the decode half of docs/SCAN_LAYERS.md).

    kpools/vpools: lists of per-layer pool arrays [num_blocks, Nkv, bs, H]
    — or, on the LayerStack path, optionally ONE stacked [N, ...] array
    each (see _pool_carry): macro-step inner loops pass the stacked form
    so the N-pool concat is paid once per dispatch, not once per token.
    ``chunk`` selects the T-token variant (speculative verify / macro-step
    internals share it).  Returns (h, pools) in the layout given.

    adapters/slots/scaling: multi-tenant LoRA — ``adapters`` maps target
    paths to slot-stacked (A [L, S, in, r], B [L, S, r, out]) with a
    LEADING LAYER AXIS; on the LayerStack path the pack rides the decode
    scan as extra per-layer xs, on the view loop each layer indexes its
    slice.  slots [B] / scaling [B] are per-batch-row (nn/lora.py).

    chain_cfg: accepted fused decode-chain schedule for the SINGLE-TOKEN
    step (ops/decode_chain.py) — invalid with chunk=True, whose T-token
    chain the searcher does not cover.
    """
    from paddle_tpu.ops import paged_attention as pa

    step = _decode_layer_paged_chunk if chunk else _decode_layer_paged
    extra_kw = {}
    if chain_cfg is not None:
        if chunk:
            raise ValueError(
                "decode-chain fusion covers the single-token step only; "
                "chunked/verify paths must not pass chain_cfg")
        extra_kw = {"chain_cfg": chain_cfg}
    if isinstance(layers, nn.LayerStack):
        # per-layer form is a list/tuple; anything else (a raw stacked
        # array or a stacked QuantPool pytree) is the carry form
        stacked_in = not isinstance(kpools, (list, tuple))
        k_state = kpools if stacked_in else pa.pool_stack(kpools)
        v_state = vpools if stacked_in else pa.pool_stack(vpools)
        if adapters is None:
            h, k_state, v_state = layers.decode_scan(
                lambda layer, hh, kc, vc: step(
                    layer, hh, cos, sin, kc, vc, tables, lens, **extra_kw),
                h, k_state, v_state)
        else:
            h, k_state, v_state = layers.decode_scan(
                lambda layer, hh, kc, vc, ad: step(
                    layer, hh, cos, sin, kc, vc, tables, lens,
                    ad=ad, slots=slots, scaling=scaling, **extra_kw),
                h, k_state, v_state, extra=adapters)
        if stacked_in:
            return h, k_state, v_state
        n = len(layers)
        return (h, [pa.pool_index(k_state, i) for i in range(n)],
                [pa.pool_index(v_state, i) for i in range(n)])
    import jax

    new_k, new_v = [], []
    for li, layer in enumerate(layers):
        ad_l = (None if adapters is None else
                jax.tree_util.tree_map(lambda a: a[li], adapters))
        h, kc, vc = step(layer, h, cos, sin, kpools[li], vpools[li],
                         tables, lens, ad=ad_l, slots=slots, scaling=scaling,
                         **extra_kw)
        new_k.append(kc)
        new_v.append(vc)
    return h, new_k, new_v


def _pool_carry(layers, kpools, vpools):
    """Per-layer pool lists -> the cheapest loop-carry form: ONE stacked
    [N, ...] pool each for a LayerStack (the macro-step scan then carries
    2 buffers instead of 2N and the decode_scan consumes them directly —
    no per-token stack/unstack), the lists unchanged for the view loop.
    Stacking is leaf-wise so quantized pools (QuantPool payload + scales)
    ride the same path."""
    from paddle_tpu.ops import paged_attention as pa

    if isinstance(layers, nn.LayerStack):
        return pa.pool_stack(kpools), pa.pool_stack(vpools)
    return list(kpools), list(vpools)


def _pool_unpack(layers, kpools, vpools):
    """Inverse of _pool_carry: back to per-layer lists for the host."""
    from paddle_tpu.ops import paged_attention as pa

    if isinstance(layers, nn.LayerStack):
        n = len(layers)
        return ([pa.pool_index(kpools, i) for i in range(n)],
                [pa.pool_index(vpools, i) for i in range(n)])
    return list(kpools), list(vpools)


def _empty_caches(config: "LlamaConfig", batch):
    """Per-layer empty naive KV caches (one constructor for generate /
    beam search / speculative decode)."""
    nkv = config.num_key_value_heads
    head_dim = config.hidden_size // config.num_attention_heads
    return [
        (paddle.zeros([batch, 0, nkv, head_dim], dtype=config.dtype),
         paddle.zeros([batch, 0, nkv, head_dim], dtype=config.dtype))
        for _ in range(config.num_hidden_layers)
    ]


def _model_forward_cached(model: "LlamaModel", input_ids, caches, position_offset=0):
    """Thread per-layer naive KV caches (prefill or decode)."""
    h = model.embed_tokens(input_ids)
    new_caches = []
    for layer, c in zip(model.layers, caches):
        h, nc = layer(h, model.rope_cos, model.rope_sin, None, kv_cache=c, position_offset=position_offset)
        new_caches.append(nc)
    return model.norm(h), new_caches


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)
            if config.dtype == "bfloat16":
                self.lm_head.to(dtype="bfloat16")

    def forward(self, input_ids, labels=None, attn_mask=None):
        if getattr(self.model, "_pp_full", False):
            logits = self.model(input_ids, attn_mask)  # stack already applied norm+head
        else:
            h = self.model(input_ids, attn_mask)
            logits = self._logits(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.astype("float32").reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]),
                ignore_index=-100,
            )
            return loss, logits
        return logits

    def _logits(self, h):
        if self.lm_head is not None:
            return self.lm_head(h)
        return paddle.matmul(h, self.model.embed_tokens.weight, transpose_y=True)

    @paddle.no_grad()
    def _speculative_decode(self, input_ids, max_new_tokens, draft_model, K):
        """Draft-and-verify greedy decoding (speculative decoding,
        Leviathan et al.; the serving tier beyond the reference repo).

        The draft proposes K tokens autoregressively; the target verifies
        all of them in ONE chunked forward over its cache (K+1 query
        tokens against cache+K keys — the bottom-right-aligned
        cross-length attention path).  Greedy acceptance: the longest
        prefix where the target's argmax agrees, then the target's own
        token at the first disagreement — so the output is EXACTLY the
        target's plain greedy decode, in ~1/(mean_accepted+1) target
        forwards.  Caches are naive (concat) so rejected tail entries
        trim with a slice.
        """
        import jax.numpy as jnp  # noqa: F811 — module alias shadow-safe

        cfg = self.config
        if draft_model.config.vocab_size != cfg.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        b, s0 = int(input_ids.shape[0]), int(input_ids.shape[1])
        self._spec_stats = {"target_forwards": 0, "draft_forwards": 0,
                            "accepted": 0, "proposed": 0}

        def _trim(caches, n):
            return [(Tensor(k._value[:, :n]), Tensor(v._value[:, :n]))
                    for k, v in caches]

        import numpy as np

        prompt = [int(t) for t in np.asarray(input_ids._value)[0]]

        # target prefill: cache covers the prompt; first token from the
        # last logit
        h, t_caches = _model_forward_cached(
            self.model, input_ids, _empty_caches(self.config, b), 0)
        self._spec_stats["target_forwards"] += 1
        first = int(jnp.argmax(
            self._logits(h[:, -1:, :])._value[0, -1, :]))
        out = [first]
        # draft prefill over the same prompt
        _, d_caches = _model_forward_cached(
            draft_model.model, input_ids,
            _empty_caches(draft_model.config, b), 0)
        self._spec_stats["draft_forwards"] += 1
        d_len = s0  # draft cache length (cache position p holds full[p])

        while len(out) < max_new_tokens:
            full = prompt + out
            base = len(full) - 1  # both caches must cover full[:base]
            # draft catch-up: one chunk over whatever the last round's
            # acceptance left unconsumed (incl. the bonus token)
            if d_len < base:
                _, d_caches = _model_forward_cached(
                    draft_model.model,
                    paddle.to_tensor([full[d_len:base]], dtype="int32"),
                    d_caches, d_len)
                self._spec_stats["draft_forwards"] += 1
                d_len = base
            k_prop = min(K, max_new_tokens - len(out))
            # ---- draft proposes k_prop tokens after `out[-1]` ----------
            proposals = []
            d_tok = out[-1]
            for j in range(k_prop):
                dh, d_caches = _model_forward_cached(
                    draft_model.model,
                    paddle.to_tensor([[d_tok]], dtype="int32"),
                    d_caches, d_len)
                self._spec_stats["draft_forwards"] += 1
                d_len += 1
                d_tok = int(jnp.argmax(
                    draft_model._logits(dh)._value[0, -1, :]))
                proposals.append(d_tok)
            # ---- target verifies the whole chunk in ONE forward --------
            chunk = [out[-1]] + proposals
            h, t_caches = _model_forward_cached(
                self.model,
                paddle.to_tensor([chunk], dtype="int32"),
                t_caches, base)
            self._spec_stats["target_forwards"] += 1
            preds = jnp.argmax(self._logits(h)._value[0], axis=-1)
            # preds[i] = target's next token after chunk[i]
            accepted = 0
            while accepted < k_prop and int(preds[accepted]) == proposals[accepted]:
                accepted += 1
            self._spec_stats["proposed"] += k_prop
            self._spec_stats["accepted"] += accepted
            # accepted proposals, then the target's own token at the first
            # disagreement (or the bonus token when everything matched)
            new = proposals[:accepted] + [int(preds[accepted])]
            out.extend(new[: max_new_tokens - len(out)])
            # trusted cache = prompt + out[:-1]: chunk[0..accepted-1] were
            # appended beyond `base`; the rejected tail trims away
            keep = base + accepted + 1
            t_caches = _trim(t_caches, keep)
            d_caches = _trim(d_caches, min(d_len, keep))
            d_len = min(d_len, keep)

        return paddle.to_tensor(
            np.asarray(out, np.int32)[None][:, :max_new_tokens])

    @paddle.no_grad()
    def _beam_search(self, input_ids, max_new_tokens, num_beams, length_penalty=0.0):
        """Beam search over the naive cache path (the reference generate()'s
        decode_strategy="beam_search", python/paddle generation lineage).

        TPU-native shape discipline: the beam frontier is a FIXED [B*K]
        batch — expand once after prefill, then each step scores [B, K*V],
        takes top-K, and reorders the caches by beam index (a gather on the
        batch axis); every step has identical shapes."""
        import jax

        cfg = self.config
        b, s0 = int(input_ids.shape[0]), int(input_ids.shape[1])
        K = int(num_beams)
        n_layers = cfg.num_hidden_layers
        nkv = cfg.num_key_value_heads
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        V = cfg.vocab_size

        empty = _empty_caches(cfg, b)
        h, caches = _model_forward_cached(self.model, input_ids, empty, 0)
        logp = jax.nn.log_softmax(
            self._logits(h[:, -1:, :])._value[:, -1, :].astype(jnp.float32), -1)

        # first step: per sequence, the K best first tokens seed the beams
        scores, first = jax.lax.top_k(logp, K)           # [B, K]
        beams = first[:, :, None].astype(jnp.int32)      # [B, K, 1]
        # expand caches to the beam frontier: [B, ...] -> [B*K, ...]
        def expand(t):
            v = t._value
            return Tensor(jnp.repeat(v, K, axis=0))
        caches = [(expand(k), expand(v)) for k, v in caches]

        for step in range(1, max_new_tokens):
            tok = Tensor(beams[:, :, -1].reshape(b * K, 1))
            h, caches = _model_forward_cached(self.model, tok, caches,
                                              s0 + step - 1)
            lp = jax.nn.log_softmax(
                self._logits(h)._value[:, -1, :].astype(jnp.float32), -1)
            total = scores.reshape(b * K, 1) + lp        # [B*K, V]
            total = total.reshape(b, K * V)
            scores, flat = jax.lax.top_k(total, K)       # [B, K]
            beam_idx = flat // V                         # [B, K] source beam
            tok_idx = (flat % V).astype(jnp.int32)
            beams = jnp.concatenate(
                [jnp.take_along_axis(beams, beam_idx[:, :, None], axis=1),
                 tok_idx[:, :, None]], axis=2)
            # reorder the beam-expanded caches by the winning source beams
            gather = (jnp.arange(b)[:, None] * K + beam_idx).reshape(-1)
            caches = [
                (Tensor(jnp.take(k._value, gather, axis=0)),
                 Tensor(jnp.take(v._value, gather, axis=0)))
                for k, v in caches
            ]

        if length_penalty:
            # no EOS termination in this path, so every beam has the same
            # length and a shared positive divisor cannot reorder them —
            # accepted for reference-signature parity, surfaced as a no-op
            import warnings

            warnings.warn(
                "length_penalty has no effect without EOS-terminated beams "
                "(all beams share length max_new_tokens)", stacklevel=2)
            scores = scores / (float(max_new_tokens) ** float(length_penalty))
        best = jnp.argmax(scores, axis=1)                # [B]
        out = jnp.take_along_axis(beams, best[:, None, None], axis=1)[:, 0, :]
        return Tensor(out)

    @paddle.no_grad()
    def generate(self, input_ids, max_new_tokens=16, cache: str = "paged",
                 block_size: int = 16, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 seed=None, decode_strategy=None, num_beams: int = 1,
                 length_penalty: float = 0.0, draft_model=None,
                 num_speculative_tokens: int = 4, decode_chunk=None):
        """Incremental decode (serving path): greedy by default; sampling
        with temperature / top-k / top-p via do_sample=True (the reference
        generate()'s decode_strategy="sampling" surface,
        python/paddle/generation lineage).

        cache="naive": per-layer concat caches (reference use_cache
        semantics; shapes grow each step, eager).
        cache="paged": block-pooled KV (reference block_multihead_attention,
        paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu):
        static shapes, so every decode step reuses ONE compiled program —
        sampling runs INSIDE it (jax.random.categorical, per-step fold_in).

        decode_chunk (paged only; None -> FLAGS_decode_chunk): macro-step
        decoding — D tokens advance per dispatch inside ONE compiled
        program (a lax.scan over the single-token step with donated
        pools), so the host round-trip and device sync amortize over D
        tokens.  Token streams are BIT-IDENTICAL for every D (greedy and
        sampled: each inner step folds the same per-step counter); the
        max_new_tokens % D tail runs through a second cached chunk size.
        """
        import numpy as np

        import jax

        if decode_strategy is not None:
            if decode_strategy not in ("sampling", "greedy_search", "beam_search"):
                raise ValueError(
                    f"decode_strategy must be 'sampling', 'greedy_search' or "
                    f"'beam_search', got {decode_strategy!r}")
            do_sample = decode_strategy == "sampling"
        if num_beams > 1:
            if do_sample:
                raise ValueError(
                    "num_beams > 1 is deterministic beam search; drop "
                    "do_sample/decode_strategy='sampling' (beam-sampling "
                    "is not implemented)")
            if draft_model is not None:
                raise ValueError(
                    "draft_model (speculative decoding) is greedy-only; "
                    "drop num_beams")
            # beam frontier runs on the naive cache path (growing shapes);
            # cache=/block_size= do not apply here
            return self._beam_search(input_ids, max_new_tokens,
                                     num_beams=num_beams,
                                     length_penalty=length_penalty)
        if draft_model is not None:
            if do_sample:
                raise ValueError(
                    "speculative decoding is greedy-only here (sampling "
                    "needs rejection-sampling acceptance; drop do_sample)")
            if int(input_ids.shape[0]) != 1:
                raise ValueError(
                    "speculative decoding supports batch size 1 at the "
                    "model-level API (per-row acceptance lengths diverge)")
            return self._speculative_decode(
                input_ids, max_new_tokens, draft_model,
                int(num_speculative_tokens))
        # decode_strategy='beam_search' with num_beams=1 IS greedy search
        if do_sample and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # validated BEFORE the (expensive) prefill; an explicit bad value
        # is loud everywhere, a bad FLAGS_decode_chunk clamps to 1 (the
        # same rule GenerationEngine applies)
        if decode_chunk is not None and int(decode_chunk) < 1:
            raise ValueError(
                f"decode_chunk must be >= 1, got {decode_chunk}")
        base_key = None
        if do_sample:
            # derive the key lazily: greedy decode must not advance the
            # global RNG stream (seed-reproducibility of existing scripts)
            if seed is not None:
                base_key = jax.random.PRNGKey(int(seed))
            else:
                from paddle_tpu._core import random as _rng

                base_key = _rng.next_key()

        def _select(logits2d, step):
            """[B, V] raw logits -> [B] next ids (greedy or sampled)."""
            if not do_sample:
                return jnp.argmax(logits2d, axis=-1)
            lg = logits2d.astype(jnp.float32) / jnp.float32(max(temperature, 1e-6))
            if top_k and top_k > 0:
                kth = jax.lax.top_k(lg, min(int(top_k), lg.shape[-1]))[0][:, -1:]
                lg = jnp.where(lg < kth, -jnp.inf, lg)
            if top_p < 1.0:
                sort = jnp.sort(lg, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sort, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # keep the smallest prefix with mass >= top_p (always >= 1)
                keep = cum - probs < jnp.float32(top_p)
                cutoff = jnp.min(jnp.where(keep, sort, jnp.inf), axis=-1, keepdims=True)
                lg = jnp.where(lg < cutoff, -jnp.inf, lg)
            return jax.random.categorical(jax.random.fold_in(base_key, step), lg, axis=-1)

        cfg = self.config
        b, s0 = int(input_ids.shape[0]), int(input_ids.shape[1])
        n_layers = cfg.num_hidden_layers
        nkv = cfg.num_key_value_heads
        head_dim = cfg.hidden_size // cfg.num_attention_heads

        # prefill with naive caches (causal), collect per-layer K/V
        empty = _empty_caches(cfg, b)
        h, caches = _model_forward_cached(self.model, input_ids, empty, 0)
        next_tok = Tensor(
            _select(self._logits(h[:, -1:, :])._value[:, -1, :], 0)
            .astype(jnp.int32)[:, None])
        out_tokens = [next_tok]

        if cache == "naive":
            cur = caches
            for step in range(1, max_new_tokens):
                h, cur = _model_forward_cached(self.model, next_tok, cur, s0 + step - 1)
                next_tok = Tensor(
                    _select(self._logits(h)._value[:, -1, :], step)
                    .astype(jnp.int32)[:, None])
                out_tokens.append(next_tok)
            return paddle.concat(out_tokens, axis=1)

        if cache != "paged":
            raise ValueError(f"cache must be 'naive' or 'paged', got {cache!r}")

        # ---- paged: pour prefill K/V into block pools -------------------
        max_len = s0 + max_new_tokens
        blocks_per_seq = -(-max_len // block_size)
        num_blocks = b * blocks_per_seq
        # seq i owns blocks [i*bps, (i+1)*bps) — a trivial allocator; real
        # serving shares the pool across requests via these same tables
        tables = jnp.asarray(
            np.arange(num_blocks, dtype=np.int32).reshape(b, blocks_per_seq)
        )
        pools = []
        pad = blocks_per_seq * block_size - s0
        for (k, v) in caches:
            kc = jnp.moveaxis(k._value, 1, 2)  # [B, Nkv, S, H]
            vc = jnp.moveaxis(v._value, 1, 2)
            kc = jnp.pad(kc, ((0, 0), (0, 0), (0, pad), (0, 0)))
            vc = jnp.pad(vc, ((0, 0), (0, 0), (0, pad), (0, 0)))
            # [B, Nkv, bps*bs, H] -> [B*bps, Nkv, bs, H] pool layout
            kc = kc.reshape(b, nkv, blocks_per_seq, block_size, head_dim)
            vc = vc.reshape(b, nkv, blocks_per_seq, block_size, head_dim)
            pools.append(
                (
                    jnp.moveaxis(kc, 1, 2).reshape(num_blocks, nkv, block_size, head_dim),
                    jnp.moveaxis(vc, 1, 2).reshape(num_blocks, nkv, block_size, head_dim),
                )
            )

        state = list(self.state_dict().values())

        def run_chunk(state_vals, kpools, vpools, tok, lens, step0, d):
            # step_once is defined INSIDE the traced function: lax.scan
            # caches the traced body jaxpr by the body's identity, so a
            # shared body object would serve one trace's closed-over bound
            # weights (tracers) to the next trace (the tail chunk)
            def step_once(carry, _):
                """One decode token — the scan body shared by every chunk
                size (bit-identical streams across D by construction)."""
                tok, kps, vps, lens, step_i = carry
                lens = lens + 1  # the new token occupies slot lens (0-based)
                hh = self.model.embed_tokens(Tensor(tok))
                hh, kps, vps = _decode_layers_paged(
                    self.model.layers, hh, self.model.rope_cos._value,
                    self.model.rope_sin._value, kps, vps, tables, lens)
                hh = self.model.norm(hh)
                logits = self._logits(hh)
                nxt = (_select(logits._value[:, -1, :], step_i)
                       .astype(tok.dtype)[:, None])
                return (nxt, kps, vps, lens, step_i + 1), nxt[:, 0]

            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with paddle.no_grad():
                    (tok, kpools, vpools, lens, _), toks = jax.lax.scan(
                        step_once, (tok, kpools, vpools, lens, step0),
                        None, length=d)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)
            return toks, tok, kpools, vpools, lens

        if decode_chunk is None:
            from paddle_tpu._core import flags as _flags

            D = max(1, int(_flags.flag("FLAGS_decode_chunk")))
        else:
            D = int(decode_chunk)
        # one executable per chunk size: the main D plus (at most) one tail
        jit_chunk = jax.jit(run_chunk, static_argnums=(6,),
                            donate_argnums=(1, 2))
        # carry form ONCE for the whole decode: a LayerStack's pools ride
        # as one stacked [N, ...] buffer each across every dispatch (the
        # per-layer lists never round-trip, so no per-dispatch restack)
        kpools, vpools = _pool_carry(
            self.model.layers, [k for k, _ in pools], [v for _, v in pools])
        lens = jnp.full((b,), s0, jnp.int32)
        tok = next_tok._value
        state_vals = [t._value for t in state]
        step = 1
        while step < max_new_tokens:
            d = min(D, max_new_tokens - step)
            toks, tok, kpools, vpools, lens = jit_chunk(
                state_vals, kpools, vpools, tok, lens, jnp.int32(step), d)
            out_tokens.append(Tensor(toks.T))  # [d, B] -> [B, d]
            step += d
        return paddle.concat(out_tokens, axis=1)


# Megatron TP kinds of the per-layer target projections — the ONE
# classification shared by shard_llama's placement walk and
# nn.lora.AdapterPack.place_over_mesh, so a serving adapter's low-rank
# factors always ride the same axis split as their base projection
# (column-parallel output dims vs row-parallel input dims).
LLAMA_TP_COL_TARGETS = ("self_attn.q_proj", "self_attn.k_proj",
                        "self_attn.v_proj", "mlp.gate_up_proj")
LLAMA_TP_ROW_TARGETS = ("self_attn.o_proj", "mlp.down_proj")


def shard_llama(model: "LlamaForCausalLM", mesh, mp_axis: str = "mp"):
    """Apply Megatron-style tensor-parallel placements to a LlamaForCausalLM.

    Capability parity with building the model from fleet mpu layers
    (reference python/paddle/distributed/fleet/layers/mpu/mp_layers.py:
    VocabParallelEmbedding :47, ColumnParallelLinear :333,
    RowParallelLinear :540) — TPU-native, the layer code is unchanged and the
    parallelism lives entirely in NamedSharding placements; GSPMD inserts the
    identity/allreduce/split/gather collectives mp_ops.py spells out by hand.

    Linear weights here are [in_features, out_features]:
      column-parallel (q/k/v, gate_up, lm_head) → Shard(1) on mp
      row-parallel (o_proj, down_proj)          → Shard(0) on mp
      vocab-parallel embedding                  → Shard(0) on mp
      norms                                     → replicated
    """
    from paddle_tpu.distributed.auto_parallel import Replicate, Shard, shard_tensor

    if mp_axis not in mesh.dim_names:
        return model
    axis_idx = mesh.dim_names.index(mp_axis)

    def place(n_dims_placement):
        pl = [Replicate()] * mesh.ndim
        pl[axis_idx] = n_dims_placement
        return pl

    def shard_param(layer, name, placement):
        p = layer._parameters.get(name)
        if p is None:
            return
        layer._parameters[name] = shard_tensor(p, mesh, place(placement), stop_gradient=p.stop_gradient)

    shard_param(model.model.embed_tokens, "weight", Shard(0))
    if isinstance(model.model.layers, nn.LayerStack):
        from paddle_tpu.nn.layer.stack import shard_stacked_params

        shard_stacked_params(
            model.model.layers, mesh, place,
            col_keys=LLAMA_TP_COL_TARGETS, row_keys=LLAMA_TP_ROW_TARGETS)
    else:
        for blk in model.model.layers:
            for col in (blk.self_attn.q_proj, blk.self_attn.k_proj, blk.self_attn.v_proj, blk.mlp.gate_up_proj):
                shard_param(col, "weight", Shard(1))
                shard_param(col, "bias", Shard(0))
            for row in (blk.self_attn.o_proj, blk.mlp.down_proj):
                shard_param(row, "weight", Shard(0))
    if model.lm_head is not None:
        shard_param(model.lm_head, "weight", Shard(1))
    return model


class _LlamaHead(nn.Layer):
    """Last pipeline stage: final RMSNorm + lm head — the layers the
    reference's SegmentLayers places on the last stage (fleet
    pp_layers.py:92)."""

    def __init__(self, norm, lm_head):
        super().__init__()
        self.norm = norm
        self.lm_head = lm_head

    def forward(self, h):
        return self.lm_head(self.norm(h))


def pipeline_llama(model: "LlamaForCausalLM", mesh, pp_axis: str = "pp",
                   num_microbatches=None, use_recompute: bool = False,
                   include_edges: bool = True, schedule: str = "1F1B",
                   num_virtual_stages: int = 1):
    """Convert the decoder stack to a pipelined stack over the 'pp' mesh axis
    (reference: PipelineLayer partition, fleet pp_layers.py:237).  Apply AFTER
    shard_llama (TP placements transfer to the stacked weights) and BEFORE
    creating the optimizer (parameters are replaced by stacked ones).

    include_edges=True pipelines the FULL model: the embedding becomes the
    first stage's extra layer and norm+lm_head the last stage's (reference
    SegmentLayers non-uniform cut, pp_layers.py:92), so token ids enter the
    pipeline and logits leave it."""
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

    if pp_axis not in mesh.dim_names:
        return model
    if isinstance(model.model.layers, nn.LayerStack):
        raise ValueError(
            "pipeline_llama: the decoder stack is a fused LayerStack "
            "(fuse_layer_stack/FLAGS_scan_layers); pipeline parallelism "
            "partitions per-layer modules — build the model with "
            "fuse_layer_stack=False to pipeline it")
    first = last = None
    if include_edges and model.lm_head is None:
        # tied embeddings would need the embedding weight on both edge
        # stages; keep the (previous, still-correct) trunk-only pipeline
        import warnings

        warnings.warn(
            "pipeline_llama: tie_word_embeddings=True cannot place the "
            "embedding on both edge stages; falling back to the trunk-only "
            "pipeline (embedding/head replicated outside the pp region)",
            stacklevel=2,
        )
        include_edges = False
    if include_edges:
        first = model.model.embed_tokens
        last = _LlamaHead(model.model.norm, model.lm_head)
    model.model.layers = PipelineStack(
        list(model.model.layers),
        mesh,
        pp_axis=pp_axis,
        num_microbatches=num_microbatches,
        use_recompute=use_recompute,
        schedule=schedule,
        num_virtual_stages=num_virtual_stages,
        first_stage=first,
        last_stage=last,
    )
    if include_edges:
        self_model = model.model
        self_model._pp_full = True
    return model


def context_parallel_llama(model: "LlamaForCausalLM", mode: str = "ring"):
    """Switch every attention layer to sequence-parallel attention
    (ring or Ulysses over the 'sep' mesh axis — reference SEP hybrid axis +
    the ring/all-to-all context-parallel recipes).  Inside an SPMD region
    with 'sep' in scope each rank holds a contiguous sequence shard: rope
    offsets become rank-relative and K/V shards rotate over ICI
    (ops/ring_attention.py).  Outside any sep scope the layers fall back to
    ordinary causal attention, so the same model object serves both."""
    if mode not in ("ring", "ulysses"):
        raise ValueError(f"mode must be ring|ulysses, got {mode!r}")
    for blk in model.model.layers:
        blk.self_attn._sep_mode = mode
    return model


def llama_tiny(**kw) -> LlamaConfig:
    cfg = dict(
        vocab_size=1024,
        hidden_size=256,
        intermediate_size=688,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=512,
    )
    cfg.update(kw)
    return LlamaConfig(**cfg)


def llama_7b(**kw) -> LlamaConfig:
    cfg = dict(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=32,
        max_position_embeddings=4096,
    )
    cfg.update(kw)
    return LlamaConfig(**cfg)
