from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaDecoderLayer,
    LlamaForCausalLM,
    LlamaModel,
    llama_7b,
    llama_tiny,
)
