from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaDecoderLayer,
    LlamaForCausalLM,
    LlamaModel,
    llama_7b,
    llama_tiny,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    BertModel,
    ErnieConfig,
    ErnieForSequenceClassification,
    ErnieModel,
    bert_tiny,
)
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    gpt_tiny,
    shard_gpt,
)
