"""GPT decoder family (learned positions, pre-LN).

Capability target: the reference's auto-parallel e2e tests are built on a
GPT pattern (test/auto_parallel/get_gpt_model.py) and PaddleNLP's GPT-2/3
models ride the same fleet stack; this is that family on the framework's nn
tier.  TPU-first: causal attention through scaled_dot_product_attention
(flash kernel on TPU), bf16-friendly, trains under jit.TrainStep and shards
with shard_gpt (Megatron placements like shard_llama)."""

from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny", "shard_gpt", "pipeline_gpt"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dropout: float = 0.1
    # run the uniform block stack as one jax.lax.scan over stacked weights
    # (nn.LayerStack; FLAGS_scan_layers forces it on) — depth-constant
    # trace/compile like models/llama.py
    fuse_layer_stack: bool = False


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_attention_heads, cfg.dropout)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc_in = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc_out = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, h):
        # is_causal routes to the flash kernel (no O(s^2) materialized mask)
        h = h + self.attn(self.ln_1(h), is_causal=True)
        h = h + self.drop(self.fc_out(F.gelu(self.fc_in(self.ln_2(h)))))
        return h


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        from paddle_tpu._core import flags as _flags

        blocks = [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)]
        if cfg.fuse_layer_stack or _flags.flag("FLAGS_scan_layers"):
            # needs_rng only when dropout actually fires: a p=0 stack keeps
            # the global RNG stream identical to the unrolled loop
            self.h = nn.LayerStack(blocks, needs_rng=cfg.dropout > 0)
        else:
            self.h = nn.LayerList(blocks)
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids):
        b, s = input_ids.shape
        if s > self.config.max_position_embeddings:
            raise ValueError(
                f"sequence length {s} exceeds max_position_embeddings "
                f"{self.config.max_position_embeddings} (jax would silently "
                f"clamp the position lookup)"
            )
        pos = paddle.arange(s, dtype="int32").unsqueeze(0).expand([b, s])
        h = self.drop(self.wte(input_ids) + self.wpe(pos))
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

        if isinstance(self.h, (PipelineStack, nn.LayerStack)):
            h = self.h(h)
        else:
            for blk in self.h:
                h = blk(h)
        return self.ln_f(h)


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)
        self.config = cfg

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        # weight-tied head (GPT-2 convention)
        logits = paddle.matmul(h, self.gpt.wte.weight, transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(
                logits[:, :-1].reshape([-1, self.config.vocab_size]).astype("float32"),
                labels[:, 1:].reshape([-1]),
            )
            return loss, logits
        return logits


def shard_gpt(model: "GPTForCausalLM", mesh, mp_axis: str = "mp"):
    """Megatron placements: fc_in + qkv column-sharded, fc_out/out_proj
    row-sharded, embeddings vocab-sharded (reference mp_layers.py roles).
    Parameters are PHYSICALLY placed (shard_tensor device_put) like
    shard_llama — not just annotated — so eager use is sharded too."""
    from paddle_tpu.distributed.auto_parallel import Replicate, Shard, shard_tensor

    if mp_axis not in mesh.dim_names:
        return model
    axis_idx = mesh.dim_names.index(mp_axis)

    def place(p):
        pl = [Replicate()] * mesh.ndim
        pl[axis_idx] = p
        return pl

    def shard_param(layer, name, p):
        param = layer._parameters.get(name)
        if param is not None:
            layer._parameters[name] = shard_tensor(
                param, mesh, place(p), stop_gradient=param.stop_gradient
            )

    shard_param(model.gpt.wte, "weight", Shard(0))
    if isinstance(model.gpt.h, nn.LayerStack):
        # stacked layout (fuse_layer_stack): iterating views would shard
        # template slots the scan never reads — place the stacked weights
        from paddle_tpu.nn.layer.stack import shard_stacked_params

        shard_stacked_params(
            model.gpt.h, mesh, place,
            col_keys=("attn.q_proj", "attn.k_proj", "attn.v_proj", "fc_in"),
            row_keys=("attn.out_proj", "fc_out"))
    else:
        for blk in model.gpt.h:
            for col in (blk.attn.q_proj, blk.attn.k_proj, blk.attn.v_proj, blk.fc_in):
                shard_param(col, "weight", Shard(1))
                shard_param(col, "bias", Shard(0))
            for row in (blk.attn.out_proj, blk.fc_out):
                shard_param(row, "weight", Shard(0))
    return model


def gpt_tiny(**kw) -> GPTConfig:
    cfg = dict(
        vocab_size=512,
        hidden_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=256,
        max_position_embeddings=128,
        dropout=0.0,
    )
    cfg.update(kw)
    return GPTConfig(**cfg)


def pipeline_gpt(model: "GPTForCausalLM", mesh, pp_axis: str = "pp",
                 num_microbatches=None, use_recompute: bool = False,
                 schedule: str = "1F1B", num_virtual_stages: int = 1):
    """Pipeline the GPT decoder trunk over the 'pp' mesh axis (reference
    PipelineLayer partition, fleet pp_layers.py:237).  GPT-2's head is
    weight-tied to wte, so the embeddings / final norm / head stay outside
    the pipelined region (the same trunk-only fallback tied-embedding LLaMA
    takes); the uniform block stack rides the scan-based SPMD engine."""
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

    if pp_axis not in mesh.dim_names:
        return model
    if isinstance(model.gpt.h, nn.LayerStack):
        raise ValueError(
            "pipeline_gpt: the block stack is a fused LayerStack "
            "(fuse_layer_stack/FLAGS_scan_layers); build the model with "
            "fuse_layer_stack=False to pipeline it")
    model.gpt.h = PipelineStack(
        list(model.gpt.h), mesh, pp_axis=pp_axis,
        num_microbatches=num_microbatches, use_recompute=use_recompute,
        schedule=schedule, num_virtual_stages=num_virtual_stages,
    )
    return model
