"""BERT / ERNIE encoder family.

Capability target: the BASELINE.md north-star finetune configs (BERT-base +
ERNIE-3.0 data-parallel finetune) — reference model definitions live in
PaddleNLP on top of the framework; here the family is built on this
framework's nn stack the same way (nn.TransformerEncoder).  ERNIE 1.0/3.0
base shares the BERT encoder architecture (different pretraining + task
heads), so ErnieModel is the same graph with its config defaults.

TPU-first notes: bf16-friendly (fp32 LayerNorm statistics come from the nn
LayerNorm), attention through scaled_dot_product_attention (flash kernel on
TPU), whole-model runs under jit.TrainStep for finetuning.
"""

from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = [
    "BertConfig",
    "BertModel",
    "BertForSequenceClassification",
    "BertForMaskedLM",
    "ErnieConfig",
    "ErnieModel",
    "ErnieForSequenceClassification",
    "bert_tiny",
]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0


ErnieConfig = BertConfig  # same encoder family (see module docstring)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = paddle.arange(s, dtype="int32").unsqueeze(0).expand([b, s])
        if token_type_ids is None:
            token_type_ids = paddle.zeros([b, s], dtype="int32")
        emb = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
            + self.token_type_embeddings(token_type_ids)
        )
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden_states):
        return paddle.tanh(self.dense(hidden_states[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size,
            config.num_attention_heads,
            config.intermediate_size,
            dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
        )
        self.encoder = nn.TransformerEncoder(layer, config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None, attention_mask=None):
        if attention_mask is None:
            attention_mask = (input_ids != self.config.pad_token_id).astype("int32")
        # additive mask broadcast over [B, S(q), N, S(k)] (BSNH attention layout)
        ext = ((1 - attention_mask.astype("float32")) * -1e4).unsqueeze(1).unsqueeze(1)
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        h = self.encoder(h, ext)
        return h, self.pooler(h)


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = nn.functional.cross_entropy(logits, labels)
            return loss, logits
        return logits


class BertForMaskedLM(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        h, _ = self.bert(input_ids, token_type_ids, attention_mask=attention_mask)
        h = self.layer_norm(nn.functional.gelu(self.transform(h)))
        logits = self.decoder(h)
        if labels is not None:
            loss = nn.functional.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]).astype("float32"),
                labels.reshape([-1]),
                ignore_index=-100,
            )
            return loss, logits
        return logits


ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification


def bert_tiny(**kw) -> BertConfig:
    cfg = dict(
        vocab_size=1024,
        hidden_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=256,
        max_position_embeddings=128,
    )
    cfg.update(kw)
    return BertConfig(**cfg)
