"""paddle.geometric equivalent (reference:
python/paddle/geometric/__init__.py — 11 exports: segment math, graph
message passing, reindex, neighbor sampling).

TPU-first: every op is a jax.ops.segment_* / gather composition — graph
message passing on TPU is exactly the gather→combine→segment-reduce
pattern XLA schedules well; no CUDA scatter-atomics emulation.  Neighbor
sampling is host-side numpy (it is data preparation, not compute)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv",
    "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _num_segments(segment_ids, out_size=None):
    if out_size is not None:
        return int(out_size)
    if isinstance(segment_ids, jax.core.Tracer):
        raise ValueError(
            "segment ops under jit need a static segment count: pass "
            "out_size=<num_segments> (max(segment_ids)+1 cannot be read "
            "from a traced array)"
        )
    ids = np.asarray(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


# segment math (reference python/paddle/geometric/math.py) -----------------

def segment_sum(data, segment_ids, out_size=None, name=None):
    d, ids = _v(data), _v(segment_ids)
    n = _num_segments(ids, out_size)
    return Tensor(jax.ops.segment_sum(d, ids, num_segments=n))


def segment_mean(data, segment_ids, out_size=None, name=None):
    d, ids = _v(data), _v(segment_ids)
    n = _num_segments(ids, out_size)
    tot = jax.ops.segment_sum(d, ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, d.dtype), ids, num_segments=n)
    cnt = cnt.reshape(cnt.shape + (1,) * (tot.ndim - cnt.ndim))
    return Tensor(tot / jnp.maximum(cnt, 1))


def segment_min(data, segment_ids, out_size=None, name=None):
    d, ids = _v(data), _v(segment_ids)
    n = _num_segments(ids, out_size)
    out = jax.ops.segment_min(d, ids, num_segments=n)
    # empty segments: paddle fills 0
    has = jax.ops.segment_sum(jnp.ones(ids.shape), ids, num_segments=n) > 0
    has = has.reshape(has.shape + (1,) * (out.ndim - has.ndim))
    return Tensor(jnp.where(has, out, 0))


def segment_max(data, segment_ids, out_size=None, name=None):
    d, ids = _v(data), _v(segment_ids)
    n = _num_segments(ids, out_size)
    out = jax.ops.segment_max(d, ids, num_segments=n)
    has = jax.ops.segment_sum(jnp.ones(ids.shape), ids, num_segments=n) > 0
    has = has.reshape(has.shape + (1,) * (out.ndim - has.ndim))
    return Tensor(jnp.where(has, out, 0))


# message passing (reference geometric/message_passing/send_recv.py) -------

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled via sum/count
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _reduce(msgs, dst, n, pool_type):
    if pool_type == "mean":
        tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(dst.shape, msgs.dtype), dst, num_segments=n)
        cnt = cnt.reshape(cnt.shape + (1,) * (tot.ndim - cnt.ndim))
        return tot / jnp.maximum(cnt, 1)
    fn = _REDUCERS[pool_type]
    out = fn(msgs, dst, num_segments=n)
    if pool_type in ("min", "max"):
        has = jax.ops.segment_sum(jnp.ones(dst.shape), dst, num_segments=n) > 0
        has = has.reshape(has.shape + (1,) * (out.ndim - has.ndim))
        out = jnp.where(has, out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None, name=None):
    """Gather x at src, reduce into dst (reference
    geometric/message_passing/send_recv.py:30)."""
    xv, src, dst = _v(x), _v(src_index), _v(dst_index)
    n = out_size or xv.shape[0]
    return Tensor(_reduce(xv[src], dst, int(n), reduce_op))


_MSG_OPS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    """Combine src features with edge features, reduce into dst (reference
    send_recv.py:156)."""
    xv, yv = _v(x), _v(y)
    src, dst = _v(src_index), _v(dst_index)
    msgs = _MSG_OPS[message_op](xv[src], yv)
    n = out_size or xv.shape[0]
    return Tensor(_reduce(msgs, dst, int(n), reduce_op))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints, no reduce (reference
    geometric/message_passing/send_recv.py:330)."""
    xv, yv = _v(x), _v(y)
    src, dst = _v(src_index), _v(dst_index)
    return Tensor(_MSG_OPS[message_op](xv[src], yv[dst]))


# reindex (reference geometric/reindex.py) ---------------------------------

def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None, name=None):
    """Compact global ids to local contiguous ids (reference reindex.py:26).

    Returns (reindex_src, reindex_dst, out_nodes): out_nodes = unique nodes
    in order [x, new neighbors]; reindex_src maps neighbors to local ids;
    reindex_dst repeats each x-node id count[i] times."""
    xa = np.asarray(_v(x))
    nbr = np.asarray(_v(neighbors))
    cnt = np.asarray(_v(count))
    id_map = {int(v): i for i, v in enumerate(xa)}
    out = list(xa)
    src_local = np.empty(len(nbr), np.int64)
    for i, v in enumerate(nbr):
        vi = int(v)
        if vi not in id_map:
            id_map[vi] = len(out)
            out.append(vi)
        src_local[i] = id_map[vi]
    dst_local = np.repeat(np.arange(len(xa), dtype=np.int64), cnt)
    return (
        Tensor(jnp.asarray(src_local)),
        Tensor(jnp.asarray(dst_local)),
        Tensor(jnp.asarray(np.asarray(out, np.int64))),
    )


def reindex_heter_graph(x, neighbors, count, value_buffer=None, index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are lists per edge type
    (reference reindex.py:150)."""
    xa = np.asarray(_v(x))
    id_map = {int(v): i for i, v in enumerate(xa)}
    out = list(xa)
    srcs, dsts = [], []
    for nbr_t, cnt_t in zip(neighbors, count):
        nbr = np.asarray(_v(nbr_t))
        cnt = np.asarray(_v(cnt_t))
        src_local = np.empty(len(nbr), np.int64)
        for i, v in enumerate(nbr):
            vi = int(v)
            if vi not in id_map:
                id_map[vi] = len(out)
                out.append(vi)
            src_local[i] = id_map[vi]
        srcs.append(src_local)
        dsts.append(np.repeat(np.arange(len(xa), dtype=np.int64), cnt))
    return (
        Tensor(jnp.asarray(np.concatenate(srcs))),
        Tensor(jnp.asarray(np.concatenate(dsts))),
        Tensor(jnp.asarray(np.asarray(out, np.int64))),
    )


# sampling (reference geometric/sampling/neighbors.py) ---------------------

def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling from CSC graph (reference
    sampling/neighbors.py:30)."""
    rowa = np.asarray(_v(row))
    ptr = np.asarray(_v(colptr))
    nodes = np.asarray(_v(input_nodes))
    eida = np.asarray(_v(eids)) if eids is not None else None
    rng = np.random.default_rng()
    out_nbr, out_cnt, out_eids = [], [], []
    for nid in nodes:
        lo, hi = int(ptr[nid]), int(ptr[nid + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        out_nbr.append(rowa[sel])
        out_cnt.append(len(sel))
        if return_eids and eida is not None:
            out_eids.append(eida[sel])
    nbrs = np.concatenate(out_nbr) if out_nbr else np.empty(0, rowa.dtype)
    res = (Tensor(jnp.asarray(nbrs)), Tensor(jnp.asarray(np.asarray(out_cnt, np.int32))))
    if return_eids:
        e = np.concatenate(out_eids) if out_eids else np.empty(0, np.int64)
        return res + (Tensor(jnp.asarray(e)),)
    return res


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False, name=None):
    """Weighted (without replacement) neighbor sampling (reference
    sampling/neighbors.py:170)."""
    rowa = np.asarray(_v(row))
    ptr = np.asarray(_v(colptr))
    w = np.asarray(_v(edge_weight))
    nodes = np.asarray(_v(input_nodes))
    eida = np.asarray(_v(eids)) if eids is not None else None
    rng = np.random.default_rng()
    out_nbr, out_cnt, out_eids = [], [], []
    for nid in nodes:
        lo, hi = int(ptr[nid]), int(ptr[nid + 1])
        deg = hi - lo
        if deg == 0:
            out_cnt.append(0)
            continue
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            p = w[lo:hi] / w[lo:hi].sum()
            sel = lo + rng.choice(deg, size=sample_size, replace=False, p=p)
        out_nbr.append(rowa[sel])
        out_cnt.append(len(sel))
        if return_eids and eida is not None:
            out_eids.append(eida[sel])
    nbrs = np.concatenate(out_nbr) if out_nbr else np.empty(0, rowa.dtype)
    res = (Tensor(jnp.asarray(nbrs)), Tensor(jnp.asarray(np.asarray(out_cnt, np.int32))))
    if return_eids:
        e = np.concatenate(out_eids) if out_eids else np.empty(0, np.int64)
        return res + (Tensor(jnp.asarray(e)),)
    return res
