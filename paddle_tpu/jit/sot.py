"""SOT-lite: bytecode-level graph capture with guards and graph breaks.

Reference: the jit/sot tier — the CPython frame-eval hook
(python/paddle/jit/sot/translate.py:99, paddle/fluid/pybind/eval_frame.c)
feeding a symbolic opcode interpreter with guards and graph-break fallback
(python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py:301,
:1457 for the break logic).

TPU-native redesign: instead of emitting rewritten bytecode, the
interpreter records straight-line tensor work into the existing static
Program machinery (static/program.py — every funnel op called with
Variables under a program_guard records itself), and each recorded segment
compiles to ONE XLA executable through the static Executor.  The
SOT-specific machinery here is exactly what plain tracing cannot do:

- **symbolic opcode interpretation** over a curated CPython 3.11/3.12
  subset: the function's real bytecode drives the capture, so Python-level
  control flow (if/for/while over PYTHON values), container ops, closures
  and method calls all behave natively;
- **graph breaks**: a jump conditioned on a symbolic tensor ends the
  current segment — the segment executes for real, the predicate becomes a
  concrete bool, and capture resumes in a fresh segment (the reference's
  BreakGraph + resume-function mechanism, trace-tree-ified); `while` over a
  symbolic predicate breaks per check, exactly like the reference's
  per-iteration break;
- **callee inlining**: plain-Python user functions, methods and hook-free
  nn.Layer forwards are interpreted in their own frame on an explicit
  frame stack (the reference's OpcodeInlineExecutor,
  python/paddle/jit/sot/opcode_translator/executor/opcode_inline_executor.py:1),
  so guards compose and graph breaks propagate at ANY call depth; a callee
  whose bytecode pre-scan shows unsupported constructs simply executes
  natively instead (safe: the decision is made before any side effect);
- **guards**: captures are cached per input signature (tensor
  shapes/dtypes + hashable python args) and per branch-decision path; a
  guard miss re-traces instead of mis-replaying;
- **fallback**: an unsupported opcode or a construct the interpreter
  cannot model marks the signature eager-only and runs the original
  function — never a crash (`opcode_executor.py`'s fallback-to-dygraph
  contract).

Scope notes vs the reference's 32k-LoC tier (documented limits, not bugs):
framework internals (paddle_tpu.*, jax, numpy) always execute natively —
they are designed to run on symbolic Variables through the apply() funnel,
so inlining them would only add interpreter surface; cell/global STORE
falls back.  Binding guards (globals read during the trace, attribute-
loaded callables, inlined callees' closure cells) are re-resolved on every
replay — rebinding a helper or monkey-patching a method re-traces instead
of replaying stale code (guard.py lineage).
"""

from __future__ import annotations

import dis
import sys
import types

import jax
import numpy as np

__all__ = ["symbolic_translate", "sot_stats", "GraphBreak", "Unsupported"]


class GraphBreak(Exception):
    """Internal: a tensor-valued predicate reached a branch opcode."""


class Unsupported(Exception):
    """Internal: opcode/construct outside the supported subset."""


_STATS = {"captures": 0, "graph_breaks": 0, "fallbacks": 0, "replays": 0,
          "inlines": 0, "guard_misses": 0}


def sot_stats():
    return dict(_STATS)


# --------------------------------------------------------------------------
# capture artifacts

class _Segment:
    """One straight-line recorded region: a static Program plus the mapping
    from interpreter state (locals/stack slots holding symbolic Variables)
    to the program's feed/fetch variables."""

    __slots__ = ("program", "feed_vars", "fetch_vars", "pred_index")

    def __init__(self, program, feed_vars, fetch_vars, pred_index=None):
        self.program = program
        self.feed_vars = feed_vars      # list[Variable] (segment inputs)
        self.fetch_vars = fetch_vars    # list[Variable] (live outputs)
        self.pred_index = pred_index    # fetch index of the branch predicate


class _Capture:
    """A traced path: segments separated by concrete branch decisions."""

    __slots__ = ("segments", "decisions", "out_builder", "guards")

    def __init__(self, segments, decisions, out_builder, guards=()):
        self.segments = segments        # list[_Segment]
        self.decisions = tuple(decisions)  # bools taken at each break
        self.out_builder = out_builder  # (fetched values of last seg) -> result
        self.guards = guards            # binding guards, see _guards_hold


# --------------------------------------------------------------------------
# binding guards (reference: sot guard chain over globals/closure cells,
# python/paddle/jit/sot/opcode_translator/executor/guard.py) — every
# trace-time binding the capture baked (globals read, attribute-loaded
# callables, inlined callees' closure cells) is re-resolved at replay;
# a mismatch re-traces instead of replaying stale code.

_MISSING = object()
_EQ_TYPES = (int, float, str, bool, bytes, type(None))


def _underlying_code(v):
    f = getattr(v, "__func__", v)
    return getattr(f, "__code__", None)


def _guard_expected(v):
    code = _underlying_code(v)
    if code is not None:
        # functions/methods: code identity + closure-cell identity — a
        # rebind to the same code but fresh cells (factory re-invocation)
        # must re-trace, because the baked constants came from those cells
        f = getattr(v, "__func__", v)
        return ("code", code, getattr(f, "__closure__", None))
    if isinstance(v, _EQ_TYPES):
        return ("eq", type(v), v)
    return ("is", v)


def _guards_hold(guards):
    for g in guards:
        kind = g[0]
        if kind == "global":
            _, gl, bl, name, exp = g
            cur = gl.get(name, _MISSING)
            if cur is _MISSING and hasattr(bl, "get"):
                cur = bl.get(name, _MISSING)
        elif kind == "attr":
            _, obj, name, exp = g
            cur = getattr(obj, name, _MISSING)
        else:  # cell
            _, cell, exp = g
            try:
                cur = cell.cell_contents
            except ValueError:
                cur = _MISSING
        if cur is _MISSING:
            return False
        ekind = exp[0]
        if ekind == "code":
            if _underlying_code(cur) is not exp[1]:
                return False
            curf = getattr(cur, "__func__", cur)
            cells, exp_cells = getattr(curf, "__closure__", None), exp[2]
            if (cells is None) != (exp_cells is None):
                return False
            if cells is not None and (
                len(cells) != len(exp_cells)
                or any(a is not b for a, b in zip(cells, exp_cells))
            ):
                return False
        elif ekind == "eq":
            if type(cur) is not exp[1] or cur != exp[2]:
                return False
        elif cur is not exp[1]:
            return False
    return True


# --------------------------------------------------------------------------
# the interpreter

_BINARY_OPS = {
    0: lambda a, b: a + b,    # NB_ADD
    1: lambda a, b: a & b,
    2: lambda a, b: a // b,
    3: lambda a, b: a << b,
    4: lambda a, b: a @ b,
    5: lambda a, b: a * b,
    6: lambda a, b: a % b,
    7: lambda a, b: a | b,
    8: lambda a, b: a ** b,
    9: lambda a, b: a >> b,
    10: lambda a, b: a - b,
    11: lambda a, b: a / b,
    12: lambda a, b: a ^ b,
    # in-place variants map to the same functional forms (the interpreter
    # rebinds the slot, which is what the bytecode does with the result)
    13: lambda a, b: a + b,
    14: lambda a, b: a & b,
    15: lambda a, b: a // b,
    16: lambda a, b: a << b,
    17: lambda a, b: a @ b,
    18: lambda a, b: a * b,
    19: lambda a, b: a % b,
    20: lambda a, b: a | b,
    21: lambda a, b: a ** b,
    22: lambda a, b: a >> b,
    23: lambda a, b: a - b,
    24: lambda a, b: a / b,
    25: lambda a, b: a ^ b,
}

_COMPARE = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _is_symbolic(v):
    from paddle_tpu.static.program import Variable

    return isinstance(v, Variable)


# opnames _step models; a callee is inline-eligible only when every
# instruction of its code object is in this set (pre-scan, decided BEFORE
# execution so a "no" costs nothing and has no side effects)
_SUPPORTED_OPS = frozenset({
    "RESUME", "NOP", "PRECALL", "CACHE", "MAKE_CELL", "COPY_FREE_VARS",
    "PUSH_EXC_INFO", "END_FOR", "POP_TOP", "COPY", "SWAP", "PUSH_NULL",
    "LOAD_FAST", "LOAD_FAST_CHECK", "LOAD_FAST_AND_CLEAR", "STORE_FAST",
    "DELETE_FAST", "LOAD_CONST", "RETURN_CONST", "RETURN_VALUE",
    "LOAD_GLOBAL", "LOAD_DEREF", "LOAD_ATTR", "LOAD_METHOD", "KW_NAMES",
    "IMPORT_NAME", "IMPORT_FROM",
    "CALL", "BINARY_OP", "UNARY_NEGATIVE", "UNARY_NOT", "UNARY_INVERT",
    "UNARY_POSITIVE", "COMPARE_OP", "IS_OP", "CONTAINS_OP",
    "FORMAT_VALUE", "BUILD_STRING",
    "BINARY_SUBSCR", "BINARY_SLICE", "BUILD_SLICE", "BUILD_TUPLE", "BUILD_LIST",
    "BUILD_MAP", "BUILD_SET", "BUILD_CONST_KEY_MAP", "LIST_EXTEND", "LIST_APPEND",
    "SET_ADD", "MAP_ADD", "UNPACK_SEQUENCE", "POP_JUMP_IF_FALSE",
    "POP_JUMP_IF_TRUE", "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE",
    "JUMP_FORWARD", "JUMP_BACKWARD", "JUMP_BACKWARD_NO_INTERRUPT",
    "GET_ITER", "FOR_ITER",
})

_INLINE_MAX_DEPTH = 12

# CO_GENERATOR | CO_COROUTINE | CO_ASYNC_GENERATOR | CO_ITERABLE_COROUTINE
_NON_PLAIN_FLAGS = 0x20 | 0x80 | 0x200 | 0x100

_UNBOUND = object()  # LOAD_FAST_AND_CLEAR's NULL stand-in


import functools as _functools


@_functools.lru_cache(maxsize=4096)
def _code_info(code):
    """(instructions, offset->index) for a code object, computed once —
    the same Block.forward is inlined per layer per trace."""
    instructions = tuple(dis.get_instructions(code))
    by_offset = {i.offset: idx for idx, i in enumerate(instructions)}
    return instructions, by_offset


@_functools.lru_cache(maxsize=4096)
def _prescan_code(code):
    if code.co_flags & _NON_PLAIN_FLAGS:
        return False
    return all(i.opname in _SUPPORTED_OPS for i in _code_info(code)[0])


def _prescan_ok(fn):
    return _prescan_code(fn.__code__)


def _inline_target(func):
    """Resolve a callee to (plain_function, prepended_args) when it is
    inline-ELIGIBLE; None -> execute natively.  User code and hook-free
    Layer forwards are inlined; framework internals (paddle_tpu.*, jax,
    numpy, builtins) run natively — they are designed to execute on
    symbolic Variables through the apply() funnel."""
    prepend = []
    if isinstance(func, types.MethodType):
        prepend = [func.__self__]
        func = func.__func__
    if not isinstance(func, types.FunctionType):
        # a hook-free nn.Layer instance: calling it == calling forward
        # (layers.py __call__ is exactly pre-hooks -> forward -> post-hooks)
        # — but ONLY when the subclass did not override __call__ or shadow
        # forward on the instance; custom __call__ bodies must run natively
        try:
            from paddle_tpu.nn import Layer as _Layer
        except ImportError:
            return None
        fwd = getattr(type(func), "forward", None)
        if (
            isinstance(func, _Layer)
            and type(func).__call__ is _Layer.__call__
            and "forward" not in vars(func)
            and fwd is not None
            and isinstance(fwd, types.FunctionType)
            and not getattr(func, "_forward_pre_hooks", True)
            and not getattr(func, "_forward_post_hooks", True)
        ):
            prepend = [func]
            func = fwd
        else:
            return None
    mod = getattr(func, "__module__", "") or ""
    root = mod.split(".", 1)[0]
    if root in ("paddle_tpu", "jax", "jaxlib", "numpy", "builtins") and not mod.startswith(
        "paddle_tpu.models"
    ):
        # model-zoo forwards are user-shaped code and benefit from breaks
        # at depth; everything else framework-side stays native
        return None
    return func, prepend


def _bind_args(fn, args, kwargs):
    """Full CPython binding (defaults, kw-only, *args/**kwargs) -> locals
    dict keyed like co_varnames.  Unsupported on any mismatch."""
    import inspect

    try:
        # follow_wrapped=False: we interpret THIS code object, so bind
        # against its own signature, not a functools.wraps'd original
        sig = inspect.Signature.from_callable(fn, follow_wrapped=False)
        ba = sig.bind(*args, **kwargs)
        ba.apply_defaults()
    except (TypeError, ValueError) as e:
        raise Unsupported(f"cannot bind arguments for {fn.__name__!r}: {e}") from e
    return dict(ba.arguments)


def _entry_tensor_list(fn, args, kwargs):
    """Top-level Tensor arguments in PARAMETER-DECLARATION order — the
    exact order the tracer's first segment uses for its feeds.  Replay must
    bind identically or keyword calls pair the wrong tensors."""
    from paddle_tpu._core.tensor import Tensor

    if isinstance(fn, types.MethodType):
        args = (fn.__self__,) + tuple(args)
        fn = fn.__func__
    loc = _bind_args(fn, args, kwargs)
    return [v for v in loc.values() if isinstance(v, Tensor)]


class _Frame:
    """One interpreted call frame (reference OpcodeInlineExecutor keeps the
    same per-frame state on its executor objects)."""

    __slots__ = ("fn", "code", "instructions", "by_offset", "globals",
                 "builtins", "closure", "cellmap", "locals", "stack",
                 "kw_names", "idx")

    def __init__(self, fn, local_vars):
        self.fn = fn
        self.code = fn.__code__
        self.instructions, self.by_offset = _code_info(self.code)
        self.globals = fn.__globals__
        b = fn.__globals__.get("__builtins__", __builtins__)
        if isinstance(b, types.ModuleType):
            b = b.__dict__
        self.builtins = b
        self.closure = {}
        self.cellmap = {}  # name -> cell object (for replay binding guards)
        if fn.__closure__:
            for name, cell in zip(self.code.co_freevars, fn.__closure__):
                try:
                    self.closure[name] = cell.cell_contents
                    self.cellmap[name] = cell
                except ValueError:  # empty cell
                    pass
        self.locals = local_vars
        self.stack: list = []
        self.kw_names = ()
        self.idx = 0


class _Interpreter:
    """Symbolically executes one function call, recording tensor work into
    Programs and breaking the graph at tensor-valued branches.  Callees are
    inlined as frames on an explicit stack when eligible, so breaks work at
    any depth."""

    def __init__(self, fn, args, kwargs):
        from paddle_tpu._core.tensor import Tensor

        if isinstance(fn, types.MethodType):  # e.g. model.forward
            args = (fn.__self__,) + tuple(args)
            fn = fn.__func__
        self.fn = fn
        root = _Frame(fn, _bind_args(fn, args, kwargs))
        self.frames: list[_Frame] = [root]
        self.segments: list[_Segment] = []
        self.decisions: list[bool] = []
        self._guards: list = []
        self._guard_keys: set = set()
        self._tensor_inputs = [
            (k, v) for k, v in root.locals.items() if isinstance(v, Tensor)
        ]

    # ------------------------------------------------------------- guards
    def _note_global_guard(self, f, name, value):
        key = ("g", id(f.globals), name)
        if key not in self._guard_keys:
            self._guard_keys.add(key)
            self._guards.append(
                ("global", f.globals, f.builtins, name, _guard_expected(value)))

    def _note_attr_guard(self, obj, name, value):
        key = ("a", id(obj), name)
        if key not in self._guard_keys:
            self._guard_keys.add(key)
            self._guards.append(("attr", obj, name, _guard_expected(value)))

    def _maybe_attr_guard(self, obj, name, value):
        """Guard attribute-loaded CALLABLES on concrete objects (method
        monkey-patching must invalidate); plain data attrs are left to the
        tensor-signature guards."""
        from paddle_tpu._core.tensor import Tensor

        if isinstance(obj, Tensor) or isinstance(value, Tensor):
            return
        if _underlying_code(value) is not None:
            self._note_attr_guard(obj, name, value)

    def _note_cell_guard(self, cell, contents):
        key = ("c", id(cell))
        if key not in self._guard_keys:
            self._guard_keys.add(key)
            self._guards.append(("cell", cell, _guard_expected(contents)))

    def _note_cell_guards(self, tfn):
        if not getattr(tfn, "__closure__", None):
            return
        for name, cell in zip(tfn.__code__.co_freevars, tfn.__closure__):
            try:
                contents = cell.cell_contents
            except ValueError:
                continue
            self._note_cell_guard(cell, contents)

    # ---------------------------------------------------------- segments
    def _begin_segment(self, concrete_tensors):
        """Open a Program whose feeds are the given concrete Tensors; the
        corresponding interpreter slots are replaced by Variables."""
        from paddle_tpu.static.program import Program

        prog = Program()
        self._prog = prog
        self._feed_vals = []
        feed_vars = []
        mapping = {}
        for t in concrete_tensors:
            aval = jax.ShapeDtypeStruct(tuple(t._value.shape), t._value.dtype)
            var = prog.add_feed(prog.new_var(aval, f"sot_in_{len(feed_vars)}"))
            feed_vars.append(var)
            self._feed_vals.append(t._value)
            mapping[id(t)] = var
        self._open_feed_vars = feed_vars
        return mapping

    def _all_slots(self):
        """Every value reachable from any frame's locals or stack."""
        out = []
        for fr in self.frames:
            out.extend(fr.locals.values())
            out.extend(fr.stack)
        return out

    @staticmethod
    def _deep_leaves(v, out, seen):
        """Collect leaves through list/tuple/dict containers (model code
        holds tensors in lists across breaks: `outs.append(layer(x))`)."""
        if isinstance(v, (list, tuple, set, frozenset)):
            if id(v) in seen:
                return
            seen.add(id(v))
            for e in v:
                _Interpreter._deep_leaves(e, out, seen)
        elif isinstance(v, dict):
            if id(v) in seen:
                return
            seen.add(id(v))
            for e in v.values():
                _Interpreter._deep_leaves(e, out, seen)
        else:
            out.append(v)

    @staticmethod
    def _deep_replace(v, repl, seen):
        """Apply `repl` to leaves through containers; lists/dicts mutate in
        place (aliases stay consistent), tuples rebuild."""
        if isinstance(v, list):
            if id(v) not in seen:
                seen.add(id(v))
                for i, e in enumerate(v):
                    v[i] = _Interpreter._deep_replace(e, repl, seen)
            return v
        if isinstance(v, tuple):
            return tuple(_Interpreter._deep_replace(e, repl, seen) for e in v)
        if isinstance(v, set):
            if id(v) not in seen:
                seen.add(id(v))
                new = {_Interpreter._deep_replace(e, repl, set()) for e in v}
                v.clear()
                v.update(new)
            return v
        if isinstance(v, frozenset):
            return frozenset(_Interpreter._deep_replace(e, repl, set()) for e in v)
        if isinstance(v, dict):
            if id(v) not in seen:
                seen.add(id(v))
                for k, e in list(v.items()):
                    v[k] = _Interpreter._deep_replace(e, repl, seen)
            return v
        return repl(v)

    def _close_segment(self, extra_fetch=()):
        """Fetch all live symbolic values (every frame's locals + stack +
        extras), execute the recorded program, and substitute concrete
        Tensors back across all frames."""
        from paddle_tpu.static.executor import Executor

        leaves: list = []
        self._deep_leaves(self._all_slots() + list(extra_fetch), leaves, set())
        live = []
        seen = set()
        for v in leaves:
            if _is_symbolic(v) and id(v) not in seen:
                seen.add(id(v))
                live.append(v)
        seg = _Segment(self._prog, self._open_feed_vars, live)
        if extra_fetch:
            # record where the predicate sits in the fetch list (it may be
            # a live local too, so it is not necessarily last)
            seg.pred_index = next(
                i for i, v in enumerate(live) if v is extra_fetch[0]
            )
        self.segments.append(seg)

        exe = Executor()
        feed = {var.name: val for var, val in zip(seg.feed_vars, self._feed_vals)}
        outs = exe.run(seg.program, feed=feed, fetch_list=live, return_numpy=False) if live else []
        subst = {id(v): o for v, o in zip(live, outs)}

        def replace(x):
            return subst[id(x)] if _is_symbolic(x) and id(x) in subst else x

        rseen: set = set()
        for fr in self.frames:
            fr.locals = {k: self._deep_replace(v, replace, rseen) for k, v in fr.locals.items()}
            fr.stack = [self._deep_replace(v, replace, rseen) for v in fr.stack]
        return seg, [replace(v) for v in extra_fetch]

    # --------------------------------------------------------------- run
    def run(self):
        import contextlib

        from paddle_tpu.static.program import program_guard
        from paddle_tpu._core.tensor import Tensor

        # first segment: all tensor arguments become feeds
        root = self.frames[0]
        mapping = self._begin_segment([t for _, t in self._tensor_inputs])
        for k, t in self._tensor_inputs:
            root.locals[k] = mapping[id(t)]

        guard = contextlib.ExitStack()
        guard.enter_context(program_guard(self._prog))
        try:
            fuel = 200_000  # runaway-interpretation bound, shared across breaks
            while True:
                fuel -= 1
                if fuel <= 0:
                    raise Unsupported("interpretation exceeded the fuel bound")
                f = self.frames[-1]
                inst = f.instructions[f.idx]
                try:
                    nxt = self._step(f, inst)
                except GraphBreak:
                    # predicate on top of stack is symbolic: end segment,
                    # concretize, take the branch on the real value — the
                    # breaking frame may be ANY depth of inlined callee
                    pred = f.stack.pop()
                    _STATS["graph_breaks"] += 1
                    guard.close()
                    seg, (pred_t,) = self._close_segment(extra_fetch=(pred,))
                    taken = bool(np.asarray(pred_t._value))
                    self.decisions.append(taken)
                    op = inst.opname
                    if op == "POP_JUMP_IF_TRUE":
                        jump = taken
                    elif op == "POP_JUMP_IF_FALSE":
                        jump = not taken
                    else:
                        raise Unsupported(f"symbolic predicate at {op}")
                    # new segment seeded from the concrete live set of
                    # every frame (containers included)
                    leaves: list = []
                    self._deep_leaves(self._all_slots(), leaves, set())
                    dedup, seen = [], set()
                    for v in leaves:
                        if isinstance(v, Tensor) and not _is_symbolic(v) and id(v) not in seen:
                            seen.add(id(v))
                            dedup.append(v)
                    mapping = self._begin_segment(dedup)

                    def replace(x):
                        return mapping.get(id(x), x) if isinstance(x, Tensor) else x

                    rseen: set = set()
                    for fr in self.frames:
                        fr.locals = {k: self._deep_replace(v, replace, rseen)
                                     for k, v in fr.locals.items()}
                        fr.stack = [self._deep_replace(v, replace, rseen)
                                    for v in fr.stack]
                    guard = contextlib.ExitStack()
                    guard.enter_context(program_guard(self._prog))
                    f.idx = f.by_offset[inst.argval] if jump else f.idx + 1
                    continue
                if nxt == "PUSHED":
                    continue  # a callee frame was inlined; resume there
                if nxt == "RETURN":
                    ret = f.stack.pop()
                    if len(self.frames) > 1:
                        self.frames.pop()
                        self.frames[-1].stack.append(ret)
                        continue
                    guard.close()
                    guard = None
                    return self._finish(ret)
                f.idx = nxt
        finally:
            if guard is not None:
                guard.close()

    def _finish(self, ret):
        """Close the final segment; build the output reconstruction."""
        from paddle_tpu._core.tensor import Tensor

        leaves, tree = jax.tree_util.tree_flatten(
            ret, is_leaf=lambda x: isinstance(x, Tensor)
        )
        sym_idx = [i for i, l in enumerate(leaves) if _is_symbolic(l)]
        sym = [leaves[i] for i in sym_idx]
        seg, fetched = self._close_segment(extra_fetch=tuple(sym))

        template = list(leaves)

        def out_builder(vals):
            out = list(template)
            for i, v in zip(sym_idx, vals):
                out[i] = v
            return jax.tree_util.tree_unflatten(tree, out)

        # rewire the last segment's fetches to exactly the returned symbols
        # (and clear the pred marker _close_segment set from extra_fetch:
        # this segment is terminal, not a branch)
        seg.fetch_vars = sym
        seg.pred_index = None
        result = out_builder(fetched)
        capture = _Capture(self.segments, self.decisions, out_builder,
                           guards=tuple(self._guards))
        return result, capture

    # -------------------------------------------------------------- steps
    def _call(self, func, args, kwargs=None):
        try:
            return func(*args, **(kwargs or {}))
        except GraphBreak:
            raise
        except Unsupported:
            raise
        except Exception as e:
            # a callee choking on symbolic values (e.g. bool(Variable),
            # .numpy()) is not modelable without inlining -> fallback
            raise Unsupported(f"call to {getattr(func, '__name__', func)!r} failed "
                              f"under symbolic execution: {e}") from e

    def _step(self, f, inst):
        op = inst.opname
        st = f.stack
        idx = f.idx

        if op in ("RESUME", "NOP", "PRECALL", "CACHE", "MAKE_CELL", "COPY_FREE_VARS",
                  "PUSH_EXC_INFO", "END_FOR"):
            return idx + 1
        if op == "POP_TOP":
            st.pop()
            return idx + 1
        if op == "COPY":
            st.append(st[-inst.arg])
            return idx + 1
        if op == "SWAP":
            st[-1], st[-inst.arg] = st[-inst.arg], st[-1]
            return idx + 1
        if op == "PUSH_NULL":
            st.append(None)
            return idx + 1
        if op in ("LOAD_FAST", "LOAD_FAST_CHECK"):
            if inst.argval not in f.locals:
                raise Unsupported(f"unbound local {inst.argval}")
            st.append(f.locals[inst.argval])
            return idx + 1
        if op == "LOAD_FAST_AND_CLEAR":  # 3.12 inlined comprehensions
            st.append(f.locals.pop(inst.argval, _UNBOUND))
            return idx + 1
        if op == "STORE_FAST":
            v = st.pop()
            if v is _UNBOUND:  # restoring a cleared, previously-unbound slot
                f.locals.pop(inst.argval, None)
            else:
                f.locals[inst.argval] = v
            return idx + 1
        if op == "DELETE_FAST":
            f.locals.pop(inst.argval, None)
            return idx + 1
        if op in ("LOAD_CONST",):
            st.append(inst.argval)
            return idx + 1
        if op == "RETURN_CONST":
            st.append(inst.argval)
            return "RETURN"
        if op == "RETURN_VALUE":
            return "RETURN"
        if op == "LOAD_GLOBAL":
            name = inst.argval
            if inst.arg & 1:  # 3.11+: low bit = push NULL before the global
                st.append(None)
            if name in f.globals:
                val = f.globals[name]
            elif name in f.builtins:
                val = f.builtins[name]
            else:
                raise Unsupported(f"unresolvable global {name}")
            self._note_global_guard(f, name, val)
            st.append(val)
            return idx + 1
        if op == "IMPORT_NAME":
            # inline `import x` / `from x import y`: a trace-time effect
            # yielding a concrete module object (vision forwards do this —
            # resnet.py's `from ...manipulation import flatten`)
            fromlist = st.pop()
            level = st.pop()
            from paddle_tpu.static.program import suspend_capture

            try:
                with suspend_capture():
                    # a FIRST import runs the module body: that must execute
                    # eagerly, not record ops into the active capture (a
                    # module-level paddle op would otherwise bake a spurious
                    # program op and cache a symbolic Variable in the module)
                    mod = __import__(inst.argval, f.globals, None,
                                     fromlist or None, level or 0)
            except ImportError as e:
                raise Unsupported(f"import {inst.argval!r} failed: {e}") from e
            st.append(mod)
            return idx + 1
        if op == "IMPORT_FROM":
            mod = st[-1]  # module stays for further IMPORT_FROMs
            try:
                st.append(getattr(mod, inst.argval))
            except AttributeError:
                import importlib

                from paddle_tpu.static.program import suspend_capture

                try:  # CPython falls back to the submodule
                    with suspend_capture():
                        # first-time submodule import runs its module body:
                        # same eager-execution rule as IMPORT_NAME above
                        st.append(importlib.import_module(
                            f"{mod.__name__}.{inst.argval}"))
                except Exception as e:  # noqa: BLE001
                    raise Unsupported(
                        f"IMPORT_FROM {inst.argval!r}: {e}") from e
            return idx + 1
        if op == "LOAD_DEREF":
            if inst.argval in f.closure:
                cell = f.cellmap.get(inst.argval)
                if cell is not None:
                    # rebinding this cell between calls must re-trace
                    self._note_cell_guard(cell, f.closure[inst.argval])
                st.append(f.closure[inst.argval])
            elif inst.argval in f.locals:
                # MAKE_CELL'd local (a cellvar) reads through locals here
                st.append(f.locals[inst.argval])
            else:
                raise Unsupported(f"unbound closure cell {inst.argval}")
            return idx + 1
        if op == "LOAD_ATTR":
            obj = st.pop()
            # the low method-load bit exists only in 3.12's LOAD_ATTR
            # encoding; on 3.11 the arg is a raw name index and testing it
            # would corrupt the stack on odd indices
            if sys.version_info >= (3, 12) and (getattr(inst, "arg", 0) & 1):
                attr = self._call(getattr, (obj, inst.argval))
                self._maybe_attr_guard(obj, inst.argval, attr)
                st.append(attr)
                st.append(None)  # self_or_null slot consumed by CALL
                # NOTE: CPython pushes (method, self); calling the bound
                # attr directly keeps CALL's layout consistent below
                st[-2], st[-1] = st[-1], st[-2]
            else:
                attr = self._call(getattr, (obj, inst.argval))
                self._maybe_attr_guard(obj, inst.argval, attr)
                st.append(attr)
            return idx + 1
        if op == "LOAD_METHOD":  # 3.11
            obj = st.pop()
            st.append(None)
            attr = self._call(getattr, (obj, inst.argval))
            self._maybe_attr_guard(obj, inst.argval, attr)
            st.append(attr)
            return idx + 1
        if op == "KW_NAMES":
            f.kw_names = inst.argval
            return idx + 1
        if op == "CALL":
            nargs = inst.arg
            kw_names = f.kw_names
            f.kw_names = ()
            args = [st.pop() for _ in range(nargs)][::-1]
            kwargs = {}
            if kw_names:
                kwvals = args[len(args) - len(kw_names):]
                args = args[: len(args) - len(kw_names)]
                kwargs = dict(zip(kw_names, kwvals))
            a = st.pop()
            b = st.pop() if st else None
            # layouts: (callable, NULL) or (NULL, callable) or bound pair
            if a is None:
                func = b
            elif b is None:
                func = a
            else:
                func, args = b, [a] + args  # (callable, self)

            # inline-eligible callee: interpret it in its own frame so
            # graph breaks inside it propagate instead of poisoning the
            # whole signature (reference opcode_inline_executor.py)
            if len(self.frames) < _INLINE_MAX_DEPTH:
                target = _inline_target(func)
                if target is not None and _prescan_ok(target[0]):
                    tfn, prepend = target
                    try:
                        loc = _bind_args(tfn, prepend + args, kwargs)
                    except Unsupported:
                        loc = None  # odd binding: run it natively instead
                    if loc is not None:
                        # rebinding the callee's closure cells must
                        # invalidate this capture (guard.py lineage); its
                        # own name binding is guarded at the load opcode
                        self._note_cell_guards(tfn)
                        f.idx = idx + 1  # resume here after the callee returns
                        self.frames.append(_Frame(tfn, loc))
                        _STATS["inlines"] += 1
                        return "PUSHED"
            st.append(self._call(func, args, kwargs))
            return idx + 1
        if op == "BINARY_OP":
            b, a = st.pop(), st.pop()
            fn = _BINARY_OPS.get(inst.arg)
            if fn is None:
                raise Unsupported(f"BINARY_OP {inst.arg}")
            st.append(self._call(fn, (a, b)))
            return idx + 1
        if op in ("UNARY_NEGATIVE", "UNARY_NOT", "UNARY_INVERT", "UNARY_POSITIVE"):
            a = st.pop()
            if op == "UNARY_NOT" and _is_symbolic(a):
                raise Unsupported("not on a symbolic tensor")
            fn = {
                "UNARY_NEGATIVE": lambda v: -v,
                "UNARY_NOT": lambda v: not v,
                "UNARY_INVERT": lambda v: ~v,
                "UNARY_POSITIVE": lambda v: +v,
            }[op]
            st.append(self._call(fn, (a,)))
            return idx + 1
        if op == "COMPARE_OP":
            b, a = st.pop(), st.pop()
            sym = inst.argval
            if sym not in _COMPARE:
                raise Unsupported(f"COMPARE_OP {sym}")
            st.append(self._call(_COMPARE[sym], (a, b)))
            return idx + 1
        if op == "IS_OP":
            b, a = st.pop(), st.pop()
            st.append((a is b) ^ bool(inst.arg))
            return idx + 1
        if op == "CONTAINS_OP":
            b, a = st.pop(), st.pop()
            if _is_symbolic(a) or _is_symbolic(b):
                raise Unsupported("containment test on symbolic tensor")
            st.append((a in b) ^ bool(inst.arg))
            return idx + 1
        if op == "BINARY_SUBSCR":
            b, a = st.pop(), st.pop()
            st.append(self._call(lambda x, i: x[i], (a, b)))
            return idx + 1
        if op == "BINARY_SLICE":  # 3.12: x[a:b] without BUILD_SLICE
            stop, start, obj = st.pop(), st.pop(), st.pop()
            st.append(self._call(lambda o, a, b: o[a:b], (obj, start, stop)))
            return idx + 1
        if op == "FORMAT_VALUE":  # f-strings (3.11/3.12 pre-3.13 encoding)
            spec = st.pop() if inst.arg & 0x04 else ""
            v = st.pop()
            if _is_symbolic(v):
                raise Unsupported("formatting a symbolic tensor")
            conv = {0: lambda x: x, 1: str, 2: repr, 3: ascii}[inst.arg & 0x03]
            st.append(format(conv(v), spec))
            return idx + 1
        if op == "BUILD_STRING":
            parts = [st.pop() for _ in range(inst.arg)][::-1]
            st.append("".join(parts))
            return idx + 1
        if op == "BUILD_SLICE":
            if inst.arg == 3:
                c, b, a = st.pop(), st.pop(), st.pop()
                st.append(slice(a, b, c))
            else:
                b, a = st.pop(), st.pop()
                st.append(slice(a, b))
            return idx + 1
        if op == "BUILD_TUPLE":
            vals = [st.pop() for _ in range(inst.arg)][::-1]
            st.append(tuple(vals))
            return idx + 1
        if op == "BUILD_LIST":
            vals = [st.pop() for _ in range(inst.arg)][::-1]
            st.append(vals)
            return idx + 1
        if op == "BUILD_MAP":
            pairs = [st.pop() for _ in range(2 * inst.arg)][::-1]
            st.append({pairs[i]: pairs[i + 1] for i in range(0, len(pairs), 2)})
            return idx + 1
        if op == "BUILD_CONST_KEY_MAP":
            keys = st.pop()
            vals = [st.pop() for _ in range(inst.arg)][::-1]
            st.append(dict(zip(keys, vals)))
            return idx + 1
        if op == "LIST_EXTEND":
            seq = st.pop()
            st[-inst.arg].extend(seq)
            return idx + 1
        if op == "LIST_APPEND":  # 3.12 inlined comprehensions
            v = st.pop()
            st[-inst.arg].append(v)
            return idx + 1
        if op == "SET_ADD":
            v = st.pop()
            st[-inst.arg].add(v)
            return idx + 1
        if op == "MAP_ADD":
            v = st.pop()
            k = st.pop()
            st[-inst.arg][k] = v
            return idx + 1
        if op == "BUILD_SET":
            vals = [st.pop() for _ in range(inst.arg)][::-1]
            st.append(set(vals))
            return idx + 1
        if op == "UNPACK_SEQUENCE":
            seq = st.pop()
            if _is_symbolic(seq):
                raise Unsupported("unpacking a symbolic tensor")
            items = list(seq)
            if len(items) != inst.arg:
                raise Unsupported("unpack arity mismatch")
            for v in reversed(items):
                st.append(v)
            return idx + 1
        if op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
            pred = st[-1]
            if _is_symbolic(pred):
                raise GraphBreak()
            pred = st.pop()
            take = bool(pred) if op == "POP_JUMP_IF_TRUE" else not bool(pred)
            return f.by_offset[inst.argval] if take else idx + 1
        if op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
            pred = st.pop()
            is_none = pred is None
            take = is_none if op == "POP_JUMP_IF_NONE" else not is_none
            return f.by_offset[inst.argval] if take else idx + 1
        if op in ("JUMP_FORWARD", "JUMP_BACKWARD", "JUMP_BACKWARD_NO_INTERRUPT"):
            return f.by_offset[inst.argval]
        if op == "GET_ITER":
            a = st.pop()
            if _is_symbolic(a):
                raise Unsupported("iterating a symbolic tensor")
            st.append(iter(a))
            return idx + 1
        if op == "FOR_ITER":
            it = st[-1]
            try:
                st.append(next(it))
                return idx + 1
            except StopIteration:
                # 3.12: jump target is END_FOR; leave iterator for END_FOR
                st.append(None)
                tgt = f.by_offset[inst.argval]
                # emulate END_FOR's double pop here and skip past it
                st.pop()
                st.pop()
                return tgt + 1
        raise Unsupported(f"opcode {op}")


# --------------------------------------------------------------------------
# public wrapper

class SOTFunction:
    """Guarded, trace-tree-cached callable (to_static(mode="sot"))."""

    def __init__(self, fn):
        self._fn = fn
        self._captures: dict = {}   # guard_sig -> {decisions: _Capture}
        self._eager_only: set = set()
        self.__name__ = getattr(fn, "__name__", "sot_fn")
        self.__doc__ = fn.__doc__

    # ------------------------------------------------------------- guards
    def _guard_sig(self, args, kwargs):
        from paddle_tpu._core.tensor import Tensor

        parts = []
        for v in list(args) + [kwargs[k] for k in sorted(kwargs)]:
            if isinstance(v, Tensor):
                parts.append(("T", tuple(v._value.shape), str(v._value.dtype)))
            else:
                try:
                    hash(v)
                    parts.append(("P", type(v).__name__, v))
                except TypeError:
                    # unhashable python arg (list/dict/ndarray config):
                    # guarding on the type alone would replay stale
                    # constants — run this call eagerly instead
                    return None
        return tuple(parts)

    # -------------------------------------------------------------- call
    def __call__(self, *args, **kwargs):
        sig = self._guard_sig(args, kwargs)
        if sig is None:  # unguardable arguments: always eager
            _STATS["fallbacks"] += 1
            return self._fn(*args, **kwargs)
        if sig in self._eager_only:
            _STATS["fallbacks"] += 1
            return self._fn(*args, **kwargs)

        tree = self._captures.get(sig)
        if tree:
            replayed = self._try_replay(tree, args, kwargs)
            if replayed is not _MISS:
                _STATS["replays"] += 1
                return replayed

        # trace (first time for this signature, or unseen branch path)
        try:
            interp = _Interpreter(self._fn, args, kwargs)
            result, capture = interp.run()
        except Exception:
            # never-crash contract: modeled Unsupported constructs AND any
            # interpreter defect fall back to eager; a genuine user error
            # reproduces in the eager run with its real traceback
            self._eager_only.add(sig)
            _STATS["fallbacks"] += 1
            return self._fn(*args, **kwargs)
        _STATS["captures"] += 1
        self._captures.setdefault(sig, {})[capture.decisions] = capture
        return result

    def _try_replay(self, tree, args, kwargs):
        """Execute cached segments, following concrete branch decisions
        between sibling captures; _MISS when the live path was never traced
        or the segment feed layout diverges (then the caller re-traces)."""
        from paddle_tpu.static.executor import Executor
        from paddle_tpu._core.tensor import Tensor

        exe = Executor()
        try:
            tensors = _entry_tensor_list(self._fn, args, kwargs)
        except Unsupported:
            return _MISS
        decisions: list[bool] = []
        guards_ok: set = set()
        carry = tensors
        seg_i = 0
        while True:
            matches = [
                c for d, c in tree.items()
                if list(d[: len(decisions)]) == decisions and len(d) >= len(decisions)
            ]
            if not matches:
                return _MISS
            current = min(matches, key=lambda c: len(c.decisions))
            if id(current) not in guards_ok:
                if current.guards and not _guards_hold(current.guards):
                    _STATS["guard_misses"] += 1
                    return _MISS  # stale binding: caller re-traces
                guards_ok.add(id(current))
            seg = current.segments[seg_i]
            if len(seg.feed_vars) != len(carry):
                return _MISS
            feed = {var.name: t._value for var, t in zip(seg.feed_vars, carry)}
            outs = exe.run(seg.program, feed=feed,
                           fetch_list=list(seg.fetch_vars), return_numpy=False)
            if seg.pred_index is None:
                # terminal segment of `current`: its decision path must be
                # exactly what we took
                if list(current.decisions) != decisions:
                    return _MISS
                return current.out_builder(outs)
            pred = bool(np.asarray(outs[seg.pred_index]._value))
            decisions.append(pred)
            nxt_candidates = [
                c for d, c in tree.items() if list(d[: len(decisions)]) == decisions
            ]
            if not nxt_candidates:
                return _MISS
            nxt = min(nxt_candidates, key=lambda c: len(c.decisions))
            nxt_seg = nxt.segments[seg_i + 1]
            # trace-time seeding: the next segment was fed every concretized
            # live tensor that remained referenced; when the predicate was
            # fetch-only (not live in a slot) it is dropped from the carry
            if len(nxt_seg.feed_vars) == len(outs):
                carry = list(outs)
            elif len(nxt_seg.feed_vars) == len(outs) - 1:
                carry = [o for i, o in enumerate(outs) if i != seg.pred_index]
            else:
                return _MISS
            seg_i += 1


_MISS = object()


def symbolic_translate(fn):
    """Wrap `fn` with the SOT-lite capture machinery (reference
    sot/translate.py symbolic_translate)."""
    if isinstance(fn, SOTFunction):
        return fn
    return SOTFunction(fn)
