"""paddle.jit equivalent (reference: python/paddle/jit/api.py:240 to_static,
python/paddle/jit/sot bytecode capture).

TPU-native design: because every op in this framework is jax-traceable and
the autograd tape composes with tracing, "dynamic-to-static" needs no CPython
frame hook — jax.jit IS the graph capture.  `to_static` wraps a callable (or
Layer) so calls are traced once per input signature and run as one compiled
XLA program, with the AST-mode dy2static transformer (jit/dy2static)
rewriting python control flow over tensors into lax.cond/while_loop.
`TrainStep` functionalizes a full imperative train step (forward,
loss.backward(), optimizer.step()) into one compiled, donated-state program —
the replacement for the reference's C++ eager hot path + fused optimizer
kernels.

CAPTURE-TIER SCOPE: the reference ships TWO capture modes — AST transform
(full graph) and SOT bytecode interception with guard-based graph breaks
(python/paddle/jit/sot/translate.py:99, eval_frame.c).  SOT exists because
the reference's eager tier cannot be traced directly, so unsupported
constructs need transparent fallback mid-function.  Here the eager tier IS
the traceable tier: every op works under jax tracing, untraceable constructs
(data-dependent shapes) raise documented errors naming the fix, and AST mode
covers control flow — so a bytecode tier would add CPython-version-coupled
machinery without new capability.  Decision: AST-only, revisit only if a
concrete workload needs guard-based partial graphs.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core import random as rng_mod
from paddle_tpu._core.autograd import no_grad
from paddle_tpu._core.tensor import Parameter, Tensor

__all__ = ["to_static", "TrainStep", "not_to_static", "save", "load", "ignore_module"]


def _host_device():
    """default_device(cpu) context, or a no-op if no cpu backend exists
    (jax_platforms pinned to an accelerator plugin only)."""
    import contextlib

    try:
        return jax.default_device(jax.devices("cpu")[0])
    except RuntimeError:
        return contextlib.nullcontext()


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _wrap(x):
    return Tensor(x) if isinstance(x, jax.Array) else x


class _StaticFunction:
    """Compiled wrapper around a function or Layer.forward.

    The whole transformed function compiles to one XLA executable per
    (training mode, arg structure, static python args) — and the call is
    routed through the `apply` funnel, so the tape can differentiate
    THROUGH the compiled program (the reference's run_program op records a
    grad op the same way, python/paddle/jit/dy2static/partial_program.py).
    Non-Tensor positional args (python ints/floats/bools) are STATIC: they
    keep exact python semantics inside (loop bounds, flags) and a new value
    triggers a recompile, like the reference's input_spec specialization.
    """

    def __init__(self, fn, layer=None, full_graph=True, backend=None):
        from paddle_tpu.jit.dy2static import ast_transform

        # AST-mode dy2static (reference ast_transformer.py): rewrite python
        # if/while/and/or/not over tensors into lax control flow converters;
        # falls back to the original fn when source is unavailable.
        self._fn = ast_transform(fn)
        self._orig_fn = fn
        self._layer = layer
        self._cache = {}

    def _state_tensors(self):
        if self._layer is None:
            return []
        return list(self._layer.state_dict().values())

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:  # jit.enable_to_static(False) escape hatch
            return self._orig_fn(*args, **kwargs)
        from paddle_tpu._core.autograd import apply

        layer = self._layer
        state = self._state_tensors()
        # array-valued kwargs are dynamic traced inputs just like positional
        # arrays; only python scalars & co. stay static
        kwargs = {
            k: (Tensor(jnp.asarray(v)) if isinstance(v, (np.ndarray, jax.Array)) else v)
            for k, v in kwargs.items()
        }
        static_kwargs = {k: v for k, v in kwargs.items() if not isinstance(v, Tensor)}
        tensor_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Tensor)}

        flat, tree = jax.tree_util.tree_flatten(
            list(args), is_leaf=lambda x: isinstance(x, Tensor)
        )
        # array-valued leaves (Tensor / ndarray / jax.Array) are DYNAMIC
        # traced inputs; only python scalars & co. are static
        flat = [
            Tensor(jnp.asarray(l)) if isinstance(l, (np.ndarray, jax.Array)) else l
            for l in flat
        ]
        t_idx = tuple(i for i, l in enumerate(flat) if isinstance(l, Tensor))
        t_set = set(t_idx)
        static_leaves = tuple(
            (i, flat[i]) for i in range(len(flat)) if i not in t_set
        )
        kw_names = tuple(sorted(tensor_kwargs))
        key_parts = [
            layer.training if layer else None, tree, t_idx, kw_names,
        ]
        cacheable = True
        try:
            # type names disambiguate 1 / 1.0 / True (equal+same hash in
            # python, but different trace-time constants)
            typed = tuple((i, type(v).__name__, v) for i, v in static_leaves)
            hash(typed)
            key_parts.append(typed)
        except TypeError:
            cacheable = False  # unhashable python leaf: compile-per-call
            key_parts.append(None)
        try:
            kw_typed = tuple(
                sorted((k, type(v).__name__, v) for k, v in static_kwargs.items())
            )
            hash(kw_typed)
            key_parts.append(kw_typed)
        except TypeError:
            cacheable = False
            key_parts.append(None)
        cache_key = tuple(key_parts)

        entry = self._cache.get(cache_key) if cacheable else None
        if entry is None:
            fn = self._fn
            n_s, n_t, n_k = len(state), len(t_idx), len(kw_names)

            # capture only the STATIC leaves (not `flat`, which holds the
            # first call's tensor buffers and would pin them for the cache
            # entry's lifetime)
            proto = [None] * len(flat)
            for i, v in static_leaves:
                proto[i] = v

            @jax.jit
            def compiled(state_vals, t_vals, kw_vals, key):
                originals = [t._value for t in state]
                try:
                    for t, v in zip(state, state_vals):
                        t._bind(v)
                    full = list(proto)
                    for i, v in zip(t_idx, t_vals):
                        full[i] = _wrap(v)
                    rebuilt = jax.tree_util.tree_unflatten(tree, full)
                    wrapped_kw = {k: _wrap(v) for k, v in zip(kw_names, kw_vals)}
                    with rng_mod.key_scope(key), no_grad():
                        out = fn(*rebuilt, **wrapped_kw, **static_kwargs)
                    return jax.tree_util.tree_map(
                        _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor)
                    )
                finally:
                    for t, v in zip(state, originals):
                        t._bind(v)

            holder = {}

            def op_fn(*vals, _key=None):
                sv = list(vals[:n_s])
                tv = list(vals[n_s:n_s + n_t])
                kv = list(vals[n_s + n_t:])
                out = compiled(sv, tv, kv, _key)
                flat_out, out_tree = jax.tree_util.tree_flatten(out)
                holder["tree"] = out_tree
                return tuple(flat_out) if len(flat_out) != 1 else flat_out[0]

            entry = (op_fn, holder)
            if cacheable:
                self._cache[cache_key] = entry
        op_fn, holder = entry

        inputs = list(state) + [flat[i] for i in t_idx] + [tensor_kwargs[k] for k in kw_names]
        key = rng_mod.next_key()
        if not inputs:  # pure-python call: nothing for the tape to track
            res = op_fn(_key=key)
            res = (
                tuple(_wrap(r) for r in res)
                if isinstance(res, tuple)
                else _wrap(res)
            )
        else:
            res = apply("dy2static_run", functools.partial(op_fn, _key=key), *inputs)
        # out structure comes from THIS call's trace (op_fn ran just now),
        # so shape-dependent output trees stay correct across shapes
        out_tree = holder["tree"]
        leaves = list(res) if isinstance(res, (tuple, list)) else [res]
        return jax.tree_util.tree_unflatten(out_tree, leaves)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              mode="ast", **kwargs):
    """Decorator/wrapper: compile a function or Layer (reference jit/api.py:240).

    mode="ast" (default): whole-function trace+jit (the AST dy2static tier).
    mode="sot": bytecode-level capture with guards and graph-break fallback
    (jit/sot.py — the reference's symbolic-opcode-translation tier)."""

    def decorate(obj):
        from paddle_tpu.nn import Layer

        if mode == "sot":
            from .sot import symbolic_translate

            if isinstance(obj, Layer):
                obj.forward = symbolic_translate(obj.forward)
                return obj
            return symbolic_translate(obj)
        if isinstance(obj, Layer):
            sf = _StaticFunction(obj.forward, layer=obj)
            obj.forward = sf
            return obj
        return _StaticFunction(obj)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TrainStep:
    """Functionalize an imperative train step into one compiled XLA program.

    Usage:
        step = TrainStep(model, optimizer, loss_fn)   # loss_fn(model, *batch)->loss
        loss = step(x, y)                             # compiled after warmup

    Step 0 runs eagerly (creates optimizer accumulator state); subsequent
    steps run a jitted program whose inputs/outputs are the flat state pytree
    (params + buffers + optimizer state), with state donated so XLA updates
    in place (HBM-neutral, like the reference's in-place optimizer kernels).
    """

    def __init__(self, model, optimizer, loss_fn, scaler=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.scaler = scaler
        self._compiled = None
        self._state = None
        self._aot = {}  # batch signature -> AOT-compiled executable

    def _collect_state(self):
        tensors = list(self.model.state_dict().values())
        tensors += self.optimizer.opt_state_tensors()
        if self.scaler is not None and self.scaler.is_enable():
            tensors += self.scaler.state_tensors()
        return tensors

    def _post_backward(self):
        """Hook between loss.backward() and optimizer.step() inside the
        traced program — ShardedTrainStep's comm/compute overlap rewrites
        gradients here (grad-sync decomposition, docs/PIPELINE.md)."""

    def _eager_step(self, *batch):
        loss = self.loss_fn(self.model, *batch)
        if self.scaler is not None and self.scaler.is_enable():
            self.scaler.scale(loss).backward()
            self.scaler.step(self.optimizer)
        else:
            loss.backward()
            self.optimizer.step()
        self.optimizer.clear_grad()
        return loss

    def _ensure_built(self):
        if self._compiled is None:
            # Materialize optimizer accumulators WITHOUT an eager
            # forward/backward (which would dispatch hundreds of per-op XLA
            # compiles — ruinous on remote-attached TPUs).  The zero-grad
            # journaled step runs on the host CPU backend (only effective for
            # host-built, uncommitted params — state already device_put to an
            # accelerator stays there); the compiled step transfers fresh
            # state to the accelerator on first call.  GradScaler state is
            # device tensors (amp/__init__.py) and joins the state list.
            params = [p for p in self.optimizer._parameter_list if not p.stop_gradient]
            with _host_device():
                self.optimizer._journaled_step(params)
            self._state = self._collect_state()
            self._build()

    @staticmethod
    def _batch_sig(batch_vals):
        leaves, tree = jax.tree_util.tree_flatten(batch_vals)
        sig = []
        for v in leaves:
            if not hasattr(v, "dtype"):
                # python-scalar leaf: normalize through jnp so the signature
                # matches warmup()'s aval-based one ('int32', not 'int')
                v = jnp.asarray(v)
            sig.append((tuple(v.shape), str(v.dtype)))
        return (tree, tuple(sig))

    def _maybe_mesh_lint(self, batch):
        """FLAGS_verify_sharding hook: statically lint the freshly built
        step (placements, collective congruence, donation contract,
        per-device memory estimate) before the first dispatch — the
        abstract analysis never launches a collective, so a placement bug
        fails HERE with a named site instead of hanging the mesh
        (static/mesh_lint.py, docs/MESH_LINT.md)."""
        from paddle_tpu._core import flags as _flags

        if not _flags.flag("FLAGS_verify_sharding"):
            return
        from paddle_tpu.static.mesh_lint import lint_train_step

        lint_train_step(self, *batch, raise_on_error=True)

    def __call__(self, *batch):
        first_build = self._compiled is None
        self._ensure_built()
        if first_build:
            self._maybe_mesh_lint(batch)
        batch_vals = jax.tree_util.tree_map(_unwrap, batch, is_leaf=lambda x: isinstance(x, Tensor))
        key = rng_mod.next_key()
        if self.optimizer._lr_scheduler is not None:
            self.optimizer._sync_lr()  # scheduler advanced eagerly between steps
        state_vals = [t._value for t in self._state]
        # signature lookup only when warmup() populated AOT executables —
        # the plain path stays free of per-step flatten cost
        step_fn = (self._aot.get(self._batch_sig(batch_vals), self._compiled)
                   if self._aot else self._compiled)
        new_state, loss_val = step_fn(state_vals, batch_vals, key)
        for t, v in zip(self._state, new_state):
            t._bind(v)
        return Tensor(loss_val)

    def lower(self, *batch):
        """AOT entry: trace the step for `batch` (Tensors, arrays, or
        jax.ShapeDtypeStructs) and return the jax Lowered object without
        running it — `.compile()` pays XLA compilation ahead of traffic."""
        self._ensure_built()

        def aval(x):
            v = _unwrap(x)
            if isinstance(v, jax.ShapeDtypeStruct):
                return v
            v = jnp.asarray(v)
            return jax.ShapeDtypeStruct(v.shape, v.dtype)

        batch_avals = jax.tree_util.tree_map(
            aval, batch, is_leaf=lambda x: isinstance(x, Tensor))
        state_avals = [jax.ShapeDtypeStruct(t._value.shape, t._value.dtype)
                       for t in self._state]
        # key aval derived WITHOUT consuming a global RNG tick: warmup must
        # not shift the training random stream
        key_aval = jax.eval_shape(lambda: jax.random.fold_in(
            jax.random.key(0), 0))
        return self._compiled.lower(state_avals, batch_avals, key_aval)

    def warmup(self, *batch):
        """Pay trace + XLA compile for `batch`'s signature before traffic
        (values or ShapeDtypeStructs; no step is executed, no state or RNG
        advances).  The executable is kept, so the first real step with
        this signature runs it directly; with FLAGS_compilation_cache_dir
        set the compile also persists across process restarts.  Returns
        self for chaining: TrainStep(...).warmup(x, y)."""
        lowered = self.lower(*batch)
        compiled = lowered.compile()

        def aval(x):
            v = _unwrap(x)
            return v if isinstance(v, jax.ShapeDtypeStruct) else jnp.asarray(v)

        batch_avals = jax.tree_util.tree_map(
            aval, batch, is_leaf=lambda x: isinstance(x, Tensor))
        self._aot[self._batch_sig(batch_avals)] = compiled
        return self

    def _build(self):
        model, optimizer, loss_fn, scaler = self.model, self.optimizer, self.loss_fn, self.scaler
        state = self._state

        @functools.partial(jax.jit, donate_argnums=(0,))
        def compiled(state_vals, batch_vals, key):
            originals = [t._value for t in state]
            grads_saved = [getattr(t, "grad", None) for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                    t.grad = None
                    t._grad_node = None
                with rng_mod.key_scope(key):
                    batch = jax.tree_util.tree_map(
                        _wrap, batch_vals, is_leaf=lambda x: isinstance(x, jax.Array)
                    )
                    loss = loss_fn(model, *batch)
                    if scaler is not None and scaler.is_enable():
                        scaler.scale(loss).backward()
                        self._post_backward()
                        scaler.step(optimizer)
                    else:
                        loss.backward()
                        self._post_backward()
                        optimizer.step()
                    optimizer.clear_grad()
                new_vals = [t._value for t in state]
                return new_vals, loss._value
            finally:
                for t, v, g in zip(state, originals, grads_saved):
                    t._bind(v)
                    t.grad = g
                    t._grad_node = None

        self._compiled = compiled


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save (reference jit/api.py:849 emits .pdmodel/.pdiparams).

    With input_spec (paddle.static.InputSpec list) the layer's forward is
    captured into a static Program and exported as the StableHLO deploy
    artifact (loadable by paddle_tpu.inference.Predictor / jit.load); params
    are also saved as .pdparams for state_dict-style reload.
    """
    from paddle_tpu.framework.io_utils import save as fsave

    state = {"state_dict": dict(layer.state_dict()), "class": type(layer).__name__}
    fsave(state, path + ".pdparams")

    if input_spec:
        import paddle_tpu.static as static

        main = static.Program()
        with static.program_guard(main):
            feeds = [
                static.data(s.name or f"x{i}", s.shape, s.dtype)
                for i, s in enumerate(input_spec)
            ]
            was_training = layer.training
            layer.eval()
            try:
                out = layer(*feeds)
            finally:
                if was_training:
                    layer.train()
            fetch = list(out) if isinstance(out, (tuple, list)) else [out]
        # forward deploy-time optimization configs (passes/precision/
        # extra_precisions — the reference jit.save's build_strategy analog)
        export_kw = {k: configs[k] for k in
                     ("passes", "precision", "extra_precisions") if k in configs}
        static.save_inference_model(path, feeds, fetch, program=main,
                                    **export_kw)


def load(path, **configs):
    """Returns a Predictor if a .pdmodel artifact exists, else the saved
    state payload."""
    import os

    if os.path.exists(path + ".pdmodel"):
        from paddle_tpu.inference import Predictor

        return Predictor(path)
    from paddle_tpu.framework.io_utils import load as fload

    return fload(path + ".pdparams")


# ----------------------------------------------------------- compat surface
# TranslatedLayer is what jit.load returns in the reference
# (python/paddle/jit/translated_layer.py); here load() returns the Predictor
# over the saved StableHLO artifact, so the name aliases that type for
# isinstance checks on loaded models.
from paddle_tpu.inference import Predictor as TranslatedLayer  # noqa: E402


def enable_to_static(flag: bool = True):
    """Globally toggle to_static capture (reference:
    python/paddle/jit/api.py enable_to_static); when off, decorated functions
    run eagerly — the debugging escape hatch."""
    global _to_static_enabled
    _to_static_enabled = bool(flag)


_to_static_enabled = True


_dy2static_log_level = 0


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """Log transformed code of dy2static (reference jit/api.py). Level > 0
    prints the AST-transformed source when to_static compiles a function."""
    global _dy2static_log_level
    _dy2static_log_level = int(level)


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """Verbosity for dy2static logging (reference parity)."""
    global _dy2static_log_level
    _dy2static_log_level = max(_dy2static_log_level, int(level))
