"""dy2static: AST-mode capture of Python control flow over tensors.

Reference: the AST transformer pipeline
(python/paddle/jit/dy2static/ast_transformer.py, transformers for
ifelse/loop/logical ops, runtime converters in convert_operators.py) whose
output runs as a run_program op.  The reference also ships SOT bytecode
capture (python/paddle/jit/sot/translate.py:99) — here AST mode is the
shipped capture tier (SURVEY.md §7 hard-parts: AST first).

TPU-native redesign: the rewritten function still executes EAGERLY op-by-op
through the normal funnel — the transform only replaces Python `if`/`while`
statements and `and`/`or`/`not` expressions with runtime converters that
dispatch on the value: concrete values keep exact Python semantics; traced
values (inside jax.jit via paddle.jit.to_static) lower to lax.cond /
lax.while_loop through paddle_tpu.static.nn.cond/while_loop.  There is no
separate "static program" artifact — jax.jit IS the program capture.

Branch/loop bodies communicate through `nonlocal` rebinding plus get/set
closures (the reference's ast transform uses the same nonlocal pattern), so
arbitrary assignments inside branches work.  Unsupported in traced branches:
`return`/`break`/`continue` inside a tensor-conditioned block (those Ifs are
left untransformed and raise the standard tracer-bool error if reached under
tracing) and variables created in only one branch.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor

__all__ = [
    "ast_transform",
    "convert_ifelse",
    "convert_while",
    "convert_logical_and",
    "convert_logical_or",
    "convert_logical_not",
]

_UNDEF = object()


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _tensorish(v):
    return isinstance(v, (Tensor, jax.Array)) or _is_tracer(v)


# --------------------------------------------------------------------------
# runtime converters (reference convert_operators.py)
# --------------------------------------------------------------------------


def convert_ifelse(pred, true_fn, false_fn, get_args, set_args, names):
    pv = _unwrap(pred)
    if not _is_tracer(pv):
        (true_fn if bool(pv) else false_fn)()
        return

    from paddle_tpu.static.control_flow import cond as _cond

    orig = get_args()

    def branch(fn):
        def run():
            set_args(orig)
            fn()  # mutates enclosing locals via nonlocal
            vals = get_args()
            out = []
            for name, o, v in zip(names, orig, vals):
                if v is _UNDEF and o is _UNDEF:
                    out.append(None)
                    continue
                if v is _UNDEF:
                    raise ValueError(
                        f"dy2static: '{name}' deleted inside a traced branch"
                    )
                out.append(Tensor(jnp.asarray(_unwrap(v))))
            return tuple(out)

        return run

    try:
        sel = _cond(Tensor(pv, stop_gradient=True), branch(true_fn), branch(false_fn))
    finally:
        set_args(orig)
    new_vals = []
    for name, o, v in zip(names, orig, sel if isinstance(sel, (tuple, list)) else (sel,)):
        new_vals.append(o if v is None else v)
    set_args(tuple(new_vals))


def convert_ifexp(pred, true_fn, false_fn):
    """`a if t else b` / folded tail returns: lazy branches; tensor
    predicates lower to lax.cond via static.control_flow.cond."""
    pv = _unwrap(pred)
    if not _is_tracer(pv):
        return true_fn() if bool(pv) else false_fn()
    from paddle_tpu.static.control_flow import cond as _cond

    return _cond(pred, true_fn, false_fn)


def convert_while(test_fn, body_fn, get_args, set_args, names):
    # concrete path: exact python semantics
    first = _unwrap(test_fn())
    if not _is_tracer(first):
        if not bool(first):
            return
        while True:
            body_fn()
            c = _unwrap(test_fn())
            if _is_tracer(c):
                raise ValueError(
                    "dy2static: while condition became traced mid-loop; make "
                    "loop state tensors before the loop"
                )
            if not bool(c):
                break
        return

    from jax import lax

    orig = get_args()
    for name, v in zip(names, orig):
        if v is _UNDEF:
            raise ValueError(
                f"dy2static: '{name}' must be defined before a traced while loop"
            )
        if not (_tensorish(v) or isinstance(v, (int, float, bool))):
            raise ValueError(
                f"dy2static: traced while loop state '{name}' must be a tensor "
                f"or number, got {type(v).__name__}"
            )

    def to_vals(vars_):
        return tuple(jnp.asarray(_unwrap(v)) for v in vars_)

    def c(vals):
        set_args(tuple(Tensor(v) for v in vals))
        r = _unwrap(test_fn())
        return r.reshape(()) != 0 if getattr(r, "dtype", None) != jnp.bool_ else r.reshape(())

    def b(vals):
        set_args(tuple(Tensor(v) for v in vals))
        body_fn()
        return to_vals(get_args())

    res = lax.while_loop(c, b, to_vals(orig))
    set_args(tuple(Tensor(v, stop_gradient=True) for v in res))


def convert_return_ifelse(pred, t_fn, f_fn):
    """Value-returning if/else where both paths return (return transformer
    analog of reference dy2static's RETURN handling)."""
    pv = _unwrap(pred)
    if not _is_tracer(pv):
        return (t_fn if bool(pv) else f_fn)()
    from paddle_tpu.static.control_flow import cond as _cond

    return _cond(Tensor(pv, stop_gradient=True), t_fn, f_fn)


def convert_range_for(range_args, body_fn, get_args, set_args, names, target_idx):
    """`for t in range(...)` (reference convert_operators' for->while):
    concrete bounds keep exact python semantics; traced bounds lower to
    lax.while_loop with the loop target carried as state."""
    args = [_unwrap(a) for a in range_args]
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    else:
        start, stop, step = args

    traced = any(_is_tracer(v) for v in (start, stop, step))
    if not traced:
        for i in range(int(start), int(stop), int(step)):
            vals = list(get_args())
            vals[target_idx] = i
            set_args(tuple(vals))
            body_fn()
        return

    from jax import lax

    orig = list(get_args())
    for name, v in zip(names, orig):
        if v is _UNDEF and name != names[target_idx]:
            raise ValueError(
                f"dy2static: '{name}' must be defined before a traced for loop"
            )
    orig[target_idx] = jnp.asarray(start, jnp.int32)

    def to_vals(vars_):
        return tuple(jnp.asarray(_unwrap(v)) for v in vars_)

    step_v = jnp.asarray(step, jnp.int32)
    stop_v = jnp.asarray(stop, jnp.int32)

    def c(vals):
        i = vals[target_idx]
        return jnp.where(step_v > 0, i < stop_v, i > stop_v)

    def b(vals):
        set_args(tuple(Tensor(v) for v in vals))
        body_fn()
        out = list(to_vals(get_args()))
        out[target_idx] = vals[target_idx] + step_v
        return tuple(out)

    res = lax.while_loop(c, b, to_vals(orig))
    final = [Tensor(v, stop_gradient=True) for v in res]
    set_args(tuple(final))


def convert_logical_and(x, y_fn):
    xv = _unwrap(x)
    if not _tensorish(xv):
        return x and y_fn()
    y = y_fn()
    return Tensor(jnp.logical_and(jnp.asarray(xv) != 0, jnp.asarray(_unwrap(y)) != 0))


def convert_logical_or(x, y_fn):
    xv = _unwrap(x)
    if not _tensorish(xv):
        return x or y_fn()
    y = y_fn()
    return Tensor(jnp.logical_or(jnp.asarray(xv) != 0, jnp.asarray(_unwrap(y)) != 0))


def convert_logical_not(x):
    xv = _unwrap(x)
    if not _tensorish(xv):
        return not x
    return Tensor(jnp.logical_not(jnp.asarray(xv) != 0))


# --------------------------------------------------------------------------
# AST transformer
# --------------------------------------------------------------------------


def _assigned_names(nodes):
    out = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Store) and node.id not in out:
                out.append(node.id)

        def visit_FunctionDef(self, node):
            pass  # don't descend into nested defs

        def visit_Lambda(self, node):
            pass

        def visit_For(self, node):
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name) and node.target.id not in out:
                out.append(node.target.id)
            self.generic_visit(node)

    for n in nodes:
        V().visit(n)
    return out


def _has_escape(nodes):
    """Return anywhere, or break/continue NOT enclosed by a nested loop
    (those belong to the inner loop, not to the block being converted)."""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            found[0] = True

        def visit_Raise(self, node):
            # a raise cannot be traced into lax.cond; leave the python `if`
            found[0] = True

        def visit_Break(self, node):
            found[0] = True

        def visit_Continue(self, node):
            found[0] = True

        def visit_For(self, node):
            # break/continue inside are local; returns/raises still escape
            if _has_return(node.body + node.orelse):
                found[0] = True

        def visit_While(self, node):
            if _has_return(node.body + node.orelse):
                found[0] = True

        def visit_FunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

    for n in nodes:
        V().visit(n)
    return found[0]


def _has_return(nodes):
    found = [False]

    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            found[0] = True

        def visit_Raise(self, node):
            found[0] = True

        def visit_FunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

    for n in nodes:
        V().visit(n)
    return found[0]


def _make_getset(names, uid):
    """Source for get/set closures over `names` (UnboundLocal-safe get)."""
    get_lines = [f"def _pt_get_{uid}():", "    _pt_vals = []"]
    for n in names:
        get_lines += [
            "    try:",
            f"        _pt_vals.append({n})",
            "    except (NameError, UnboundLocalError):",
            "        _pt_vals.append(_pt_rt._UNDEF)",
        ]
    get_lines.append("    return tuple(_pt_vals)")
    set_lines = [f"def _pt_set_{uid}(_pt_vals):"]
    if names:
        set_lines.append(f"    nonlocal {', '.join(names)}")
        for i, n in enumerate(names):
            set_lines += [
                f"    if _pt_vals[{i}] is not _pt_rt._UNDEF:",
                f"        {n} = _pt_vals[{i}]",
            ]
    else:
        set_lines.append("    pass")
    return "\n".join(get_lines), "\n".join(set_lines)


def _all_paths_return(stmts):
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return _all_paths_return(last.body) and _all_paths_return(last.orelse)
    return False


_RET_UID = [0]


def _merge_returns(stmts):
    """Rewrite `if c: ... return A` (+ trailing code as the implicit else)
    into `return convert_return_ifelse(c, t_fn, f_fn)` when both paths
    return.  Recurses into nested bodies first."""
    out = []
    i = 0
    while i < len(stmts):
        st = stmts[i]
        for attr in ("body", "orelse", "finalbody"):
            if hasattr(st, attr) and getattr(st, attr):
                setattr(st, attr, _merge_returns(getattr(st, attr)))
        if isinstance(st, ast.If) and _all_paths_return(st.body):
            trailing = stmts[i + 1 :]
            # the implicit-else trailing block may itself hold if-return
            # chains (e.g. a python-bool early return followed by a
            # tensor-predicate return): merge it too
            orelse = st.orelse if st.orelse else _merge_returns(trailing)
            if _all_paths_return(orelse):
                _RET_UID[0] += 1
                uid = _RET_UID[0]
                t_def = ast.parse(f"def _pt_rett_{uid}():\n    pass").body[0]
                t_def.body = list(st.body)
                f_def = ast.parse(f"def _pt_retf_{uid}():\n    pass").body[0]
                f_def.body = list(orelse)
                ret = ast.parse(
                    f"return _pt_rt.convert_return_ifelse(_pt_rtest_{uid}, _pt_rett_{uid}, _pt_retf_{uid})"
                ).body[0]
                assign = ast.Assign(
                    targets=[ast.Name(id=f"_pt_rtest_{uid}", ctx=ast.Store())], value=st.test
                )
                for n in (assign, t_def, f_def, ret):
                    ast.copy_location(n, st)
                    ast.fix_missing_locations(n)
                out += [assign, t_def, f_def, ret]
                if not st.orelse:
                    return out  # trailing stmts consumed as the else branch
                i += 1
                continue
        out.append(st)
        i += 1
    return out


def _init_guard(name):
    """`try: name = name / except: name = _UNDEF` — binds `name` in the
    enclosing scope so the branch functions' `nonlocal` declarations compile,
    without disturbing an existing value."""
    return ast.parse(
        f"try:\n    {name} = {name}\n"
        f"except (NameError, UnboundLocalError):\n    {name} = _pt_rt._UNDEF"
    ).body[0]


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self, fn_locals):
        self._uid = 0
        self._fn_locals = fn_locals  # names assigned anywhere in the function

    def _next(self):
        self._uid += 1
        return self._uid

    # ---- logical ops in any expression position
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "convert_logical_and" if isinstance(node.op, ast.And) else "convert_logical_or"
        expr = node.values[0]
        for right in node.values[1:]:
            lam = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=right,
            )
            expr = ast.Call(
                func=ast.Attribute(value=ast.Name(id="_pt_rt", ctx=ast.Load()), attr=op, ctx=ast.Load()),
                args=[expr, lam],
                keywords=[],
            )
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                ast.Call(
                    func=ast.Attribute(value=ast.Name(id="_pt_rt", ctx=ast.Load()), attr="convert_logical_not", ctx=ast.Load()),
                    args=[node.operand],
                    keywords=[],
                ),
                node,
            )
        return node

    # ---- conditional expressions
    def visit_IfExp(self, node):
        self.generic_visit(node)

        def lam(body):
            return ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=body,
            )

        return ast.copy_location(
            ast.Call(
                func=ast.Attribute(value=ast.Name(id="_pt_rt", ctx=ast.Load()),
                                   attr="convert_ifexp", ctx=ast.Load()),
                args=[node.test, lam(node.body), lam(node.orelse)],
                keywords=[],
            ),
            node,
        )

    # ---- if statements
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node  # python `if` kept; traced use raises tracer-bool
        uid = self._next()
        names = [n for n in _assigned_names(node.body + node.orelse) if not n.startswith("_pt_")]
        get_src, set_src = _make_getset(names, uid)
        true_def = ast.parse(f"def _pt_true_{uid}():\n    pass").body[0]
        false_def = ast.parse(f"def _pt_false_{uid}():\n    pass").body[0]
        nl = [ast.Nonlocal(names=list(names))] if names else []
        true_def.body = nl + (node.body or [ast.Pass()])
        false_def.body = list(nl) + (node.orelse or [ast.Pass()])
        get_def = ast.parse(get_src).body[0]
        set_def = ast.parse(set_src).body[0]
        call = ast.parse(
            f"_pt_rt.convert_ifelse(_pt_test_{uid}, _pt_true_{uid}, _pt_false_{uid}, "
            f"_pt_get_{uid}, _pt_set_{uid}, {tuple(names)!r})"
        ).body[0]
        assign_test = ast.Assign(
            targets=[ast.Name(id=f"_pt_test_{uid}", ctx=ast.Store())], value=node.test
        )
        out = [_init_guard(n) for n in names]
        out += [assign_test, true_def, false_def, get_def, set_def, call]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out

    # ---- for-range statements (reference for->while transform)
    def visit_For(self, node):
        self.generic_visit(node)
        is_range = (
            isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and not node.iter.keywords
            and isinstance(node.target, ast.Name)
            and not node.orelse
        )
        if not is_range or _has_escape(node.body):
            return node
        uid = self._next()
        names = _assigned_names(node.body)
        tgt = node.target.id
        if tgt in names:
            names.remove(tgt)
        names = [tgt] + names  # target first (target_idx=0)
        get_src, set_src = _make_getset(names, uid)
        body_def = ast.parse(f"def _pt_body_{uid}():\n    pass").body[0]
        nl_names = _assigned_names(node.body) + [tgt]
        body_def.body = [ast.Nonlocal(names=sorted(set(nl_names)))] + (node.body or [ast.Pass()])
        get_def = ast.parse(get_src).body[0]
        set_def = ast.parse(set_src).body[0]
        args_tuple = ast.Tuple(elts=list(node.iter.args), ctx=ast.Load())
        call = ast.parse(
            f"_pt_rt.convert_range_for(_PT_ARGS_, _pt_body_{uid}, "
            f"_pt_get_{uid}, _pt_set_{uid}, {tuple(names)!r}, 0)"
        ).body[0]
        call.value.args[0] = args_tuple
        out = [_init_guard(n) for n in names]
        out += [body_def, get_def, set_def, call]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out

    # ---- while statements
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse:
            return node
        uid = self._next()
        # loop state = names assigned in the body; condition-only reads stay
        # plain closures (rebinding them to Tensors would break later python
        # uses like range(n))
        names = _assigned_names(node.body)
        get_src, set_src = _make_getset(names, uid)
        test_def = ast.parse(f"def _pt_test_{uid}():\n    pass").body[0]
        test_def.body = [ast.Return(value=node.test)]
        body_def = ast.parse(f"def _pt_body_{uid}():\n    pass").body[0]
        nl = [ast.Nonlocal(names=list(_assigned_names(node.body)))] if _assigned_names(node.body) else []
        body_def.body = nl + (node.body or [ast.Pass()])
        get_def = ast.parse(get_src).body[0]
        set_def = ast.parse(set_src).body[0]
        call = ast.parse(
            f"_pt_rt.convert_while(_pt_test_{uid}, _pt_body_{uid}, "
            f"_pt_get_{uid}, _pt_set_{uid}, {tuple(names)!r})"
        ).body[0]
        out = [_init_guard(n) for n in names]
        out += [test_def, body_def, get_def, set_def, call]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out


def ast_transform(fn):
    """Rewrite fn's control flow; returns the transformed function (or fn
    unchanged when source is unavailable / transform fails)."""
    func = fn.__func__ if inspect.ismethod(fn) else fn
    if getattr(func, "_pt_dy2static_done", False) or getattr(func, "_not_to_static", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(func))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return fn
        # strip decorators (they already ran to produce `fn`)
        fdef.decorator_list = []
        fdef.body = _merge_returns(fdef.body)
        fn_locals = set(_assigned_names(fdef.body))
        fn_locals.update(a.arg for a in fdef.args.args)
        fn_locals.update(a.arg for a in fdef.args.posonlyargs)
        fn_locals.update(a.arg for a in fdef.args.kwonlyargs)
        if fdef.args.vararg:
            fn_locals.add(fdef.args.vararg.arg)
        if fdef.args.kwarg:
            fn_locals.add(fdef.args.kwarg.arg)
        new_tree = _ControlFlowTransformer(fn_locals).visit(tree)
        ast.fix_missing_locations(new_tree)
        from paddle_tpu import jit as _jit_mod

        if getattr(_jit_mod, "_dy2static_log_level", 0) > 0:
            # paddle.jit.set_code_level: print the transformed source
            print(f"[dy2static] transformed code of {func.__name__}:\n{ast.unparse(new_tree)}")
        code = compile(new_tree, filename=f"<dy2static {func.__name__}>", mode="exec")
        from paddle_tpu.jit import dy2static as _rt

        # keep the ORIGINAL globals mapping live: names defined after
        # decoration (forward refs, recursion, monkeypatching) must resolve
        glb = func.__globals__
        glb["_pt_rt"] = _rt
        # free variables: rebuild with the original closure cells
        fcode = next(
            c for c in code.co_consts
            if isinstance(c, types.CodeType) and c.co_name == func.__name__
        )
        closure = func.__closure__
        if closure is not None and fcode.co_freevars != func.__code__.co_freevars:
            # transform changed the free-variable set; bail out
            return fn
        new_func = types.FunctionType(fcode, glb, func.__name__, func.__defaults__, closure)
        new_func.__kwdefaults__ = func.__kwdefaults__
        new_func._pt_dy2static_done = True
        new_func.__wrapped__ = func
        if inspect.ismethod(fn):
            return types.MethodType(new_func, fn.__self__)
        return new_func
    except (OSError, TypeError, SyntaxError, StopIteration):
        return fn
