"""KL-divergence registry (reference: python/paddle/distribution/kl.py —
register_kl decorator + dispatch by most-derived matching pair, plus the
exponential-family Bregman fallback)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _t, _v
from .distributions import (
    Bernoulli,
    Beta,
    Categorical,
    Dirichlet,
    Gamma,
    Geometric,
    Gumbel,
    Laplace,
    LogNormal,
    MultivariateNormal,
    Normal,
    Poisson,
    Uniform,
)

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    """Decorator registering fn(p, q) for the class pair (reference kl.py:64)."""

    def wrap(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return wrap


def _dispatch(p, q):
    matches = [
        (pc, qc)
        for (pc, qc) in _REGISTRY
        if isinstance(p, pc) and isinstance(q, qc)
    ]
    if not matches:
        return None
    # most-derived match: minimal by (mro distance)
    def score(pair):
        pc, qc = pair
        return (type(p).__mro__.index(pc), type(q).__mro__.index(qc))

    return _REGISTRY[min(matches, key=score)]


def kl_divergence(p, q):
    """KL(p || q) (reference kl.py:29)."""
    fn = _dispatch(p, q)
    if fn is not None:
        return fn(p, q)
    if isinstance(p, ExponentialFamily) and type(p) is type(q):
        return _kl_expfamily(p, q)
    raise NotImplementedError(
        f"no KL rule registered for ({type(p).__name__}, {type(q).__name__})"
    )


def _kl_expfamily(p, q):
    """Bregman divergence of the log-normalizer (reference kl.py:207)."""
    p_nat = tuple(_v(t) for t in p._natural_parameters)
    q_nat = tuple(_v(t) for t in q._natural_parameters)
    p_log_norm = p._log_normalizer(*p_nat)
    grads = jax.grad(lambda ps: jnp.sum(p._log_normalizer(*ps)))(p_nat)
    q_log_norm = q._log_normalizer(*q_nat)
    kl = q_log_norm - p_log_norm
    for pn, qn, g in zip(p_nat, q_nat, grads):
        kl = kl - (qn - pn) * g
    return _t(kl)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _t(0.5 * (vr + t1 - 1 - jnp.log(vr)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    r = jnp.log((q.high - q.low) / (p.high - p.low))
    return _t(jnp.where((q.low <= p.low) & (p.high <= q.high), r, jnp.inf))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    eps = 1e-8
    pp = jnp.clip(p.probs, eps, 1 - eps)
    qq = jnp.clip(q.probs, eps, 1 - eps)
    return _t(pp * (jnp.log(pp) - jnp.log(qq)) + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return _t(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def lbeta(a, b):
        return jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)

    pa, pb, qa, qb = p.alpha, p.beta, q.alpha, q.beta
    return _t(
        lbeta(qa, qb)
        - lbeta(pa, pb)
        + (pa - qa) * jsp.digamma(pa)
        + (pb - qb) * jsp.digamma(pb)
        + (qa - pa + qb - pb) * jsp.digamma(pa + pb)
    )


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    pa, qa = p.concentration, q.concentration
    pa0 = jnp.sum(pa, -1)
    return _t(
        jsp.gammaln(pa0)
        - jsp.gammaln(jnp.sum(qa, -1))
        - jnp.sum(jsp.gammaln(pa), -1)
        + jnp.sum(jsp.gammaln(qa), -1)
        + jnp.sum((pa - qa) * (jsp.digamma(pa) - jsp.digamma(pa0)[..., None]), -1)
    )


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    pa, pb, qa, qb = p.concentration, p.rate, q.concentration, q.rate
    return _t(
        (pa - qa) * jsp.digamma(pa)
        - jsp.gammaln(pa)
        + jsp.gammaln(qa)
        + qa * (jnp.log(pb) - jnp.log(qb))
        + pa * (qb - pb) / pb
    )


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    # log(b2/b1) + |μ1−μ2|/b2 + (b1/b2)·exp(−|μ1−μ2|/b1) − 1
    scale_ratio = p.scale / q.scale
    loc_diff = jnp.abs(p.loc - q.loc)
    return _t(
        -jnp.log(scale_ratio)
        + loc_diff / q.scale
        + scale_ratio * jnp.exp(-loc_diff / p.scale)
        - 1
    )


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    return _t(
        (jnp.log(p.probs) - jnp.log(q.probs))
        + (1 - p.probs) / p.probs * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs))
    )


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return _t(p.rate * (jnp.log(p.rate) - jnp.log(q.rate)) - p.rate + q.rate)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _t(0.5 * (vr + t1 - 1 - jnp.log(vr)))


@register_kl(Gumbel, Gumbel)
def _kl_gumbel(p, q):
    # KL for Gumbel(loc, scale): standard closed form
    _E = 0.5772156649015329
    beta_ratio = p.scale / q.scale
    return _t(
        jnp.log(q.scale)
        - jnp.log(p.scale)
        + _E * (beta_ratio - 1)
        + jnp.exp((q.loc - p.loc) / q.scale) * jnp.exp(jsp.gammaln(beta_ratio + 1))
        - 1
        + (p.loc - q.loc) / q.scale
    )


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    d = p.loc.shape[-1]
    q_tril = q.scale_tril
    p_tril = p.scale_tril
    diff = q.loc - p.loc
    # tr(Σq⁻¹ Σp) via triangular solves
    m = jax.scipy.linalg.solve_triangular(q_tril, p_tril, lower=True)
    tr = jnp.sum(m**2, axis=(-2, -1))
    y = jax.scipy.linalg.solve_triangular(q_tril, diff[..., None], lower=True)[..., 0]
    maha = jnp.sum(y**2, -1)
    logdet_q = jnp.sum(jnp.log(jnp.diagonal(q_tril, axis1=-2, axis2=-1)), -1)
    logdet_p = jnp.sum(jnp.log(jnp.diagonal(p_tril, axis1=-2, axis2=-1)), -1)
    return _t(0.5 * (tr + maha - d) + logdet_q - logdet_p)
