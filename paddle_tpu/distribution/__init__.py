"""paddle.distribution equivalent (reference:
python/paddle/distribution/__init__.py — 17 exports + 13 transforms).
Implemented TPU-first on jnp/jax.scipy with functional PRNG sampling and
reparameterized rsample; also includes Gamma/Exponential/Poisson/StudentT/
Binomial/MultivariateNormal/ContinuousBernoulli which later reference
snapshots export."""

from .distribution import (  # noqa: F401
    Distribution,
    ExponentialFamily,
    Independent,
    TransformedDistribution,
)
from .distributions import (  # noqa: F401
    Bernoulli,
    Beta,
    Binomial,
    Categorical,
    Cauchy,
    ContinuousBernoulli,
    Dirichlet,
    Exponential,
    Gamma,
    Geometric,
    Gumbel,
    Laplace,
    LogNormal,
    Multinomial,
    MultivariateNormal,
    Normal,
    Poisson,
    StudentT,
    Uniform,
)
from .kl import kl_divergence, register_kl  # noqa: F401
from .transform import *  # noqa: F401,F403
from . import transform  # noqa: F401

__all__ = [
    "Bernoulli",
    "Beta",
    "Binomial",
    "Categorical",
    "Cauchy",
    "ContinuousBernoulli",
    "Dirichlet",
    "Distribution",
    "Exponential",
    "ExponentialFamily",
    "Gamma",
    "Geometric",
    "Gumbel",
    "Independent",
    "Laplace",
    "LogNormal",
    "Multinomial",
    "MultivariateNormal",
    "Normal",
    "Poisson",
    "StudentT",
    "TransformedDistribution",
    "Uniform",
    "kl_divergence",
    "register_kl",
]
__all__ += transform.__all__
