"""Concrete distributions (reference: python/paddle/distribution/*.py —
normal.py, uniform.py, beta.py, bernoulli.py, categorical.py, cauchy.py,
dirichlet.py, geometric.py, gumbel.py, laplace.py, lognormal.py,
multinomial.py).  TPU-first: pure jnp math, functional PRNG sampling,
reparameterized rsample where the pathwise derivative exists (gamma/beta/
dirichlet use jax.random.gamma's implicit reparameterization)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, TransformedDistribution, _t, _v

__all__ = [
    "Bernoulli",
    "Beta",
    "Binomial",
    "Categorical",
    "Cauchy",
    "ContinuousBernoulli",
    "Dirichlet",
    "Exponential",
    "Gamma",
    "Geometric",
    "Gumbel",
    "Laplace",
    "LogNormal",
    "Multinomial",
    "MultivariateNormal",
    "Normal",
    "Poisson",
    "StudentT",
    "Uniform",
]

_EULER = 0.5772156649015329


def _broadcast(*xs):
    arrs = [_v(x) for x in xs]
    arrs = [
        a.astype(jnp.result_type(float)) if not jnp.issubdtype(a.dtype, jnp.inexact) else a
        for a in arrs
    ]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [jnp.broadcast_to(a, shape) for a in arrs], shape


class Normal(ExponentialFamily):
    """reference python/paddle/distribution/normal.py:33"""

    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _broadcast(loc, scale)
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def variance(self):
        return _t(self.scale**2)

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        eps = jax.random.normal(self._key(), sh, self.loc.dtype)
        return _t(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _v(value)
        var = self.scale**2
        return _t(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale))

    def cdf(self, value):
        return _t(0.5 * (1 + jsp.erf((_v(value) - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, q):
        return _t(self.loc + self.scale * math.sqrt(2) * jsp.erfinv(2 * _v(q) - 1))

    @property
    def _natural_parameters(self):
        return (self.loc / self.scale**2, -0.5 / self.scale**2)

    def _log_normalizer(self, eta1, eta2):
        return -(eta1**2) / (4 * eta2) - 0.5 * jnp.log(-2 * eta2)

    @property
    def _mean_carrier_measure(self):
        return 0.5 * math.log(2 * math.pi)


class LogNormal(TransformedDistribution):
    """reference python/paddle/distribution/lognormal.py:25"""

    def __init__(self, loc, scale, name=None):
        from .transform import ExpTransform

        base = Normal(loc, scale)
        self.loc, self.scale = base.loc, base.scale
        super().__init__(base, [ExpTransform()])

    @property
    def mean(self):
        return _t(jnp.exp(self.loc + self.scale**2 / 2))

    @property
    def variance(self):
        s2 = self.scale**2
        return _t((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def entropy(self):
        return _t(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) + self.loc)


class Uniform(Distribution):
    """reference python/paddle/distribution/uniform.py:34"""

    def __init__(self, low, high, name=None):
        (self.low, self.high), shape = _broadcast(low, high)
        super().__init__(shape)

    @property
    def mean(self):
        return _t((self.low + self.high) / 2)

    @property
    def variance(self):
        return _t((self.high - self.low) ** 2 / 12)

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        u = jax.random.uniform(self._key(), sh, self.low.dtype)
        return _t(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _v(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _t(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _t(jnp.log(self.high - self.low))

    def cdf(self, value):
        v = _v(value)
        return _t(jnp.clip((v - self.low) / (self.high - self.low), 0.0, 1.0))


class Bernoulli(ExponentialFamily):
    """reference python/paddle/distribution/bernoulli.py:40 (probs param)."""

    def __init__(self, probs, name=None):
        (self.probs,), shape = _broadcast(probs)
        self.probs = self.probs.astype(jnp.result_type(float))
        super().__init__(shape)

    @property
    def logits(self):
        return _t(jnp.log(self.probs) - jnp.log1p(-self.probs))

    @property
    def mean(self):
        return _t(self.probs)

    @property
    def variance(self):
        return _t(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        return _t(jax.random.bernoulli(self._key(), self.probs, sh).astype(self.probs.dtype))

    def rsample(self, shape=(), temperature=1.0):
        # Gumbel-softmax style relaxation (reference bernoulli.py rsample)
        sh = self._extend_shape(tuple(shape))
        u = jax.random.uniform(self._key(), sh, self.probs.dtype, 1e-6, 1 - 1e-6)
        logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        noise = jnp.log(u) - jnp.log1p(-u)
        return _t(jax.nn.sigmoid((logits + noise) / temperature))

    def log_prob(self, value):
        v = _v(value)
        eps = 1e-8
        p = jnp.clip(self.probs, eps, 1 - eps)
        return _t(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        eps = 1e-8
        p = jnp.clip(self.probs, eps, 1 - eps)
        return _t(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    @property
    def _natural_parameters(self):
        return (jnp.log(self.probs / (1 - self.probs)),)

    def _log_normalizer(self, eta):
        return jnp.log1p(jnp.exp(eta))

    @property
    def _mean_carrier_measure(self):
        return 0.0


class Categorical(Distribution):
    """reference python/paddle/distribution/categorical.py:33 (logits param)."""

    def __init__(self, logits, name=None):
        self.logits = _v(logits).astype(jnp.result_type(float))
        super().__init__(self.logits.shape[:-1])
        self._n = self.logits.shape[-1]

    @property
    def probs(self):
        return _t(jax.nn.softmax(self.logits, axis=-1))

    def sample(self, shape=()):
        sh = tuple(shape) + self._batch_shape
        return _t(jax.random.categorical(self._key(), self.logits, shape=sh))

    def log_prob(self, value):
        v = _v(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return _t(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def probabilities(self, value):
        return self.prob(value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return _t(-jnp.sum(p * logp, axis=-1))


class Beta(ExponentialFamily):
    """reference python/paddle/distribution/beta.py:22"""

    def __init__(self, alpha, beta, name=None):
        (self.alpha, self.beta), shape = _broadcast(alpha, beta)
        self.alpha = self.alpha.astype(jnp.result_type(float))
        self.beta = self.beta.astype(jnp.result_type(float))
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _t(self.alpha * self.beta / (s**2 * (s + 1)))

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        k1, k2 = jax.random.split(self._key())
        ga = jax.random.gamma(k1, jnp.broadcast_to(self.alpha, sh))
        gb = jax.random.gamma(k2, jnp.broadcast_to(self.beta, sh))
        return _t(ga / (ga + gb))

    def sample(self, shape=()):
        return _t(jax.lax.stop_gradient(_v(self.rsample(shape))))

    def log_prob(self, value):
        v = _v(value)
        return _t(
            (self.alpha - 1) * jnp.log(v)
            + (self.beta - 1) * jnp.log1p(-v)
            - (jsp.gammaln(self.alpha) + jsp.gammaln(self.beta) - jsp.gammaln(self.alpha + self.beta))
        )

    def entropy(self):
        a, b = self.alpha, self.beta
        lbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
        return _t(
            lbeta
            - (a - 1) * jsp.digamma(a)
            - (b - 1) * jsp.digamma(b)
            + (a + b - 2) * jsp.digamma(a + b)
        )


class Dirichlet(ExponentialFamily):
    """reference python/paddle/distribution/dirichlet.py:22"""

    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration).astype(jnp.result_type(float))
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    @property
    def mean(self):
        return _t(self.concentration / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return _t(m * (1 - m) / (a0 + 1))

    def rsample(self, shape=()):
        sh = tuple(shape) + self._batch_shape
        return _t(jax.random.dirichlet(self._key(), self.concentration, sh))

    def log_prob(self, value):
        v = _v(value)
        a = self.concentration
        return _t(
            jnp.sum((a - 1) * jnp.log(v), -1)
            + jsp.gammaln(jnp.sum(a, -1))
            - jnp.sum(jsp.gammaln(a), -1)
        )

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(a0)
        return _t(lnB + (a0 - k) * jsp.digamma(a0) - jnp.sum((a - 1) * jsp.digamma(a), -1))


class Gamma(ExponentialFamily):
    """reference python/paddle/distribution (gamma via exponential_family)."""

    def __init__(self, concentration, rate, name=None):
        (self.concentration, self.rate), shape = _broadcast(concentration, rate)
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.concentration / self.rate)

    @property
    def variance(self):
        return _t(self.concentration / self.rate**2)

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        g = jax.random.gamma(self._key(), jnp.broadcast_to(self.concentration, sh))
        return _t(g / self.rate)

    def log_prob(self, value):
        v = _v(value)
        a, b = self.concentration, self.rate
        return _t(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jsp.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return _t(a - jnp.log(b) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a))


class Exponential(Gamma):
    """Exponential(rate) = Gamma(1, rate)."""

    def __init__(self, rate, name=None):
        super().__init__(jnp.ones_like(_v(rate)), rate)
        self.rate = _v(rate)

    def cdf(self, value):
        return _t(-jnp.expm1(-self.rate * _v(value)))


class Laplace(Distribution):
    """reference python/paddle/distribution/laplace.py:25"""

    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _broadcast(loc, scale)
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def variance(self):
        return _t(2 * self.scale**2)

    @property
    def stddev(self):
        return _t(math.sqrt(2) * self.scale)

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        u = jax.random.uniform(self._key(), sh, self.loc.dtype, -0.5 + 1e-7, 0.5)
        return _t(self.loc - self.scale * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _v(value)
        return _t(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self):
        return _t(1 + jnp.log(2 * self.scale))

    def cdf(self, value):
        v = _v(value)
        z = (v - self.loc) / self.scale
        return _t(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, q):
        qv = _v(q)
        a = qv - 0.5
        return _t(self.loc - self.scale * jnp.sign(a) * jnp.log1p(-2 * jnp.abs(a)))


class Gumbel(Distribution):
    """reference python/paddle/distribution/gumbel.py:26"""

    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _broadcast(loc, scale)
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.loc + self.scale * _EULER)

    @property
    def variance(self):
        return _t(math.pi**2 / 6 * self.scale**2)

    def rsample(self, shape=()):
        sh = tuple(shape) + self._batch_shape
        g = jax.random.gumbel(self._key(), sh, self.loc.dtype)
        return _t(self.loc + self.scale * g)

    def sample(self, shape=()):
        return _t(jax.lax.stop_gradient(_v(self.rsample(shape))))

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _t(jnp.log(self.scale) + 1 + _EULER)

    def cdf(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(jnp.exp(-jnp.exp(-z)))


class Cauchy(Distribution):
    """reference python/paddle/distribution/cauchy.py:25"""

    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _broadcast(loc, scale)
        super().__init__(shape)

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        u = jax.random.uniform(self._key(), sh, self.loc.dtype, 1e-7, 1 - 1e-7)
        return _t(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(-math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z**2))

    def entropy(self):
        return _t(jnp.log(4 * math.pi * self.scale))

    def cdf(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(jnp.arctan(z) / math.pi + 0.5)


class Geometric(Distribution):
    """reference python/paddle/distribution/geometric.py:25 — number of
    failures before the first success, support {0, 1, 2, ...}."""

    def __init__(self, probs, name=None):
        (self.probs,), shape = _broadcast(probs)
        self.probs = self.probs.astype(jnp.result_type(float))
        super().__init__(shape)

    @property
    def mean(self):
        # failures-before-first-success convention (matches log_prob/cdf)
        return _t((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _t((1 - self.probs) / self.probs**2)

    @property
    def stddev(self):
        return _t(jnp.sqrt(1 - self.probs) / self.probs)

    def sample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        u = jax.random.uniform(self._key(), sh, self.probs.dtype, 1e-7, 1 - 1e-7)
        return _t(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        k = _v(value)
        return _t(k * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def pmf(self, k):
        return _t(jnp.exp(_v(self.log_prob(k))))

    def entropy(self):
        p = self.probs
        q = 1 - p
        return _t(-(q * jnp.log(q) + p * jnp.log(p)) / p)

    def cdf(self, k):
        return _t(1 - jnp.power(1 - self.probs, jnp.floor(_v(k)) + 1))


class Multinomial(Distribution):
    """reference python/paddle/distribution/multinomial.py:22"""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _v(probs).astype(jnp.result_type(float))
        self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        sh = tuple(shape) + self._batch_shape
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            self._key(), logits, shape=(self.total_count,) + sh
        )
        k = self.probs.shape[-1]
        onehot = jax.nn.one_hot(draws, k, dtype=self.probs.dtype)
        return _t(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        v = _v(value)
        logits = jnp.log(self.probs)
        return _t(
            jsp.gammaln(jnp.asarray(self.total_count + 1.0))
            - jnp.sum(jsp.gammaln(v + 1), -1)
            + jnp.sum(v * logits, -1)
        )

    def entropy(self):
        # exact entropy via support enumeration is exponential; use the
        # standard sum over marginal terms (matches reference's approach of
        # computing from log_prob on sampled support for small n)
        n = self.total_count
        p = self.probs
        # H = -Σ_x P(x) log P(x); use the known decomposition
        # H = log(n! ) ... for capability we approximate with large-n normal
        # fallback only when needed; here compute by enumeration for small k*n
        raise NotImplementedError(
            "Multinomial.entropy has no closed form; use kl_divergence or "
            "Monte-Carlo estimates"
        )


class MultivariateNormal(Distribution):
    """Full-covariance MVN (reference exposes via paddle.distribution in
    later snapshots; included for completeness)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None, name=None):
        self.loc = _v(loc).astype(jnp.result_type(float))
        if scale_tril is not None:
            self.scale_tril = _v(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = jnp.linalg.cholesky(_v(covariance_matrix))
        else:
            raise ValueError("need covariance_matrix or scale_tril")
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def covariance_matrix(self):
        return _t(self.scale_tril @ jnp.swapaxes(self.scale_tril, -1, -2))

    @property
    def variance(self):
        return _t(jnp.sum(self.scale_tril**2, axis=-1))

    def rsample(self, shape=()):
        sh = tuple(shape) + self._batch_shape + self._event_shape
        eps = jax.random.normal(self._key(), sh, self.loc.dtype)
        return _t(self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril, eps))

    def log_prob(self, value):
        d = self.loc.shape[-1]
        diff = _v(value) - self.loc
        y = jax.scipy.linalg.solve_triangular(self.scale_tril, diff[..., None], lower=True)[..., 0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), -1)
        return _t(-0.5 * jnp.sum(y**2, -1) - half_logdet - 0.5 * d * math.log(2 * math.pi))

    def entropy(self):
        d = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), -1)
        return _t(0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet)


class Poisson(ExponentialFamily):
    """Poisson(rate) — counts per interval."""

    def __init__(self, rate, name=None):
        (self.rate,), shape = _broadcast(rate)
        self.rate = self.rate.astype(jnp.result_type(float))
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.rate)

    @property
    def variance(self):
        return _t(self.rate)

    def sample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        return _t(jax.random.poisson(self._key(), self.rate, sh).astype(self.rate.dtype))

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        v = _v(value)
        return _t(v * jnp.log(self.rate) - self.rate - jsp.gammaln(v + 1))

    def entropy(self):
        # series approximation valid for moderate rate; exact via enumeration
        # for small rates
        r = self.rate
        small = r * (1 - jnp.log(r))
        ks = jnp.arange(0, 64, dtype=r.dtype)
        lp = ks[:, None] * jnp.log(r.reshape(-1)) - r.reshape(-1) - jsp.gammaln(ks + 1)[:, None]
        exact = -jnp.sum(jnp.exp(lp) * lp, axis=0).reshape(r.shape)
        big = 0.5 * jnp.log(2 * math.pi * math.e * r) - 1 / (12 * r)
        return _t(jnp.where(r < 16.0, exact, big) + 0 * small)


class StudentT(Distribution):
    """Student-t with df, loc, scale."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        (self.df, self.loc, self.scale), shape = _broadcast(df, loc, scale)
        super().__init__(shape)

    @property
    def mean(self):
        return _t(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = self.scale**2 * self.df / (self.df - 2)
        return _t(jnp.where(self.df > 2, v, jnp.where(self.df > 1, jnp.inf, jnp.nan)))

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        t = jax.random.t(self._key(), jnp.broadcast_to(self.df, sh), sh)
        return _t(self.loc + self.scale * t)

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        nu = self.df
        return _t(
            jsp.gammaln((nu + 1) / 2)
            - jsp.gammaln(nu / 2)
            - 0.5 * jnp.log(nu * math.pi)
            - jnp.log(self.scale)
            - (nu + 1) / 2 * jnp.log1p(z**2 / nu)
        )

    def entropy(self):
        nu = self.df
        return _t(
            (nu + 1) / 2 * (jsp.digamma((nu + 1) / 2) - jsp.digamma(nu / 2))
            + 0.5 * jnp.log(nu)
            + jsp.betaln(nu / 2, jnp.asarray(0.5))
            + jnp.log(self.scale)
        )


class Binomial(Distribution):
    """Binomial(total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        (self.probs,), shape = _broadcast(probs)
        self.probs = self.probs.astype(jnp.result_type(float))
        super().__init__(shape)

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        draws = jax.random.bernoulli(
            self._key(), self.probs, (self.total_count,) + sh
        )
        return _t(jnp.sum(draws, axis=0).astype(self.probs.dtype))

    def log_prob(self, value):
        k = _v(value)
        n = float(self.total_count)
        p = jnp.clip(self.probs, 1e-8, 1 - 1e-8)
        return _t(
            jsp.gammaln(jnp.asarray(n + 1.0))
            - jsp.gammaln(k + 1)
            - jsp.gammaln(n - k + 1)
            + k * jnp.log(p)
            + (n - k) * jnp.log1p(-p)
        )


class ContinuousBernoulli(Distribution):
    """Continuous Bernoulli on [0,1] (reference
    python/paddle/distribution/continuous_bernoulli.py)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        (self.probs,), shape = _broadcast(probs)
        self.probs = self.probs.astype(jnp.result_type(float))
        self._lims = lims
        super().__init__(shape)

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm(self):
        # C(p) = 2 atanh(1-2p) / (1-2p) for p != 0.5, else 2
        p = self.probs
        safe = jnp.where(self._outside(), p, 0.4)
        c = 2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe)
        # Taylor near 1/2: C ≈ 2 + (1-2p)^2 * 2/3
        x = 1 - 2 * p
        taylor = 2 + x**2 * (2 / 3) + x**4 * (2 / 5)
        return jnp.log(jnp.where(self._outside(), c, taylor))

    @property
    def mean(self):
        p = self.probs
        safe = jnp.where(self._outside(), p, 0.4)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        x = 1 - 2 * p
        taylor = 0.5 - x / 6  # first-order expansion near 1/2
        return _t(jnp.where(self._outside(), m, taylor))

    @property
    def variance(self):
        p = self.probs
        safe = jnp.where(self._outside(), p, 0.4)
        v = safe * (safe - 1) / (1 - 2 * safe) ** 2 + 1 / (2 * jnp.arctanh(1 - 2 * safe)) ** 2
        taylor = 1 / 12 - (1 - 2 * p) ** 2 / 60
        return _t(jnp.where(self._outside(), v, taylor))

    def log_prob(self, value):
        v = _v(value)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        return _t(v * jnp.log(p) + (1 - v) * jnp.log1p(-p) + self._log_norm())

    def rsample(self, shape=()):
        sh = self._extend_shape(tuple(shape))
        u = jax.random.uniform(self._key(), sh, self.probs.dtype, 1e-6, 1 - 1e-6)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        # inverse CDF: log1p(u*(p/(1-p) - 1)) / log(p/(1-p)), expanded near 1/2
        x = 1 - 2 * p
        ratio = p / (1 - p)
        safe_ratio = jnp.where(self._outside(), ratio, 2.0)
        icdf = jnp.where(
            self._outside(),
            jnp.log1p(u * (safe_ratio - 1)) / jnp.log(safe_ratio),
            u - u * (1 - u) * x,
        )
        return _t(icdf)

    def sample(self, shape=()):
        return _t(jax.lax.stop_gradient(_v(self.rsample(shape))))
