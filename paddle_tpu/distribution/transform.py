"""Bijective transforms for TransformedDistribution (reference:
python/paddle/distribution/transform.py — 13 exported transforms).  Pure
jnp so forward/inverse/log-det are traceable and differentiable."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import _t, _v

__all__ = [
    "Transform",
    "AbsTransform",
    "AffineTransform",
    "ChainTransform",
    "ExpTransform",
    "IndependentTransform",
    "PowerTransform",
    "ReshapeTransform",
    "SigmoidTransform",
    "SoftmaxTransform",
    "StackTransform",
    "StickBreakingTransform",
    "TanhTransform",
]


class Transform:
    """Base transform (reference transform.py:46)."""

    _event_dim = 0

    def forward(self, x):
        return _t(self._forward(_v(x)))

    def inverse(self, y):
        return _t(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return _t(self._fldj(_v(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _v(y)
        return _t(-self._fldj(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass hooks on raw arrays
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right-inverse (positive branch), matching reference

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _v(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """Not bijective; maps reals → simplex via softmax, inverse = log
    (reference transform.py SoftmaxTransform)."""

    _event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("SoftmaxTransform is not injective")


class StickBreakingTransform(Transform):
    """Reals^(K-1) → K-simplex (reference transform.py StickBreakingTransform)."""

    _event_dim = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zp = jnp.concatenate([jnp.zeros_like(z[..., :1]), z], -1)
        cum = jnp.cumprod(1 - zp[..., :-1], -1)
        head = z * cum
        last = jnp.prod(1 - zp[..., 1:], -1, keepdims=True)
        return jnp.concatenate([head, last], -1)

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - jnp.concatenate([jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], -1)
        z = y[..., :-1] / rem
        offset = z.shape[-1] - jnp.cumsum(jnp.ones_like(z), -1) + 1
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, x):
        # Jacobian is lower-triangular with diag dy_k/dx_k =
        # z_k(1-z_k)·Π_{j<k}(1-z_j), so
        # log|det| = Σ_k [log z_k + log(1-z_k) + Σ_{j<k} log(1-z_j)]
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        xo = x - jnp.log(offset)
        z = jax.nn.sigmoid(xo)
        log1mz = jnp.log1p(-z)
        excl_cum = jnp.cumsum(log1mz, -1) - log1mz  # Σ_{j<k} log(1-z_j)
        log_dz = -jax.nn.softplus(-xo) - jax.nn.softplus(xo)  # log z + log(1-z)
        return jnp.sum(log_dz + excl_cum, -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._event_dim = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[: len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[: len(shape) - n]) + self.in_event_shape


class IndependentTransform(Transform):
    """Promote a transform to treat trailing dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._event_dim = base._event_dim + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ldj = self.base._fldj(x)
        return jnp.sum(ldj, axis=tuple(range(-self.rank, 0)))


class StackTransform(Transform):
    """Apply a sequence of transforms to slices along an axis."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, fn_name, x):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p.squeeze(self.axis)) for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _fldj(self, x):
        return self._map("_fldj", x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._event_dim = max([t._event_dim for t in self.transforms], default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = None
        for t in self.transforms:
            ldj = t._fldj(x)
            # reduce per-transform jacobian to this chain's event rank
            extra = self._event_dim - t._event_dim
            if extra > 0 and ldj.ndim >= extra:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            total = ldj if total is None else total + ldj
            x = t._forward(x)
        return total if total is not None else jnp.zeros_like(x)

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)
