"""Probability distribution base classes.

Capability parity with the reference's ``paddle.distribution`` package
(python/paddle/distribution/distribution.py, exponential_family.py,
independent.py, transformed_distribution.py), built TPU-first: every method
is pure jnp (traceable under jit/vmap), sampling consumes functional PRNG
keys from the framework generator, and rsample is reparameterized wherever
the math allows so gradients flow through samples.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core import random as rng
from paddle_tpu._core.tensor import Tensor

__all__ = ["Distribution", "ExponentialFamily", "Independent", "TransformedDistribution"]


def _v(x):
    """Unwrap Tensor → jnp array (accepts python scalars / numpy too)."""
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


def _t(x):
    return Tensor(x)


class Distribution:
    """Base for all distributions (reference
    python/paddle/distribution/distribution.py:40).

    batch_shape: shape of independent parameterizations.
    event_shape: shape of a single draw.
    """

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(int(s) for s in batch_shape)
        self._event_shape = tuple(int(s) for s in event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return _t(jnp.sqrt(_v(self.variance)))

    def sample(self, shape=()):
        """Draw without gradient tracking."""
        s = self.rsample(shape)
        return _t(jax.lax.stop_gradient(_v(s)))

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _t(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # helpers ---------------------------------------------------------------
    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    @staticmethod
    def _key():
        return rng.next_key()

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self._batch_shape}, event_shape={self._event_shape})"


class ExponentialFamily(Distribution):
    """Exponential-family base with Bregman-divergence entropy via autodiff
    (reference python/paddle/distribution/exponential_family.py:24): entropy
    = A(θ) - <θ, ∇A(θ)> + E[-log h(x)] computed from the log-normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        # H = A(θ) − Σᵢ θᵢ·∂A/∂θᵢ + E[−log h(x)]; grad of the summed
        # log-normalizer gives the per-batch-element ∂A/∂θᵢ
        nparams = tuple(_v(p) for p in self._natural_parameters)
        grads = jax.grad(lambda ps: jnp.sum(self._log_normalizer(*ps)))(nparams)
        result = self._log_normalizer(*nparams) + self._mean_carrier_measure
        for p, g in zip(nparams, grads):
            result = result - p * g
        return _t(result)


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference
    python/paddle/distribution/independent.py:22)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted = int(reinterpreted_batch_rank)
        shape = base.batch_shape + base.event_shape
        nb = len(base.batch_shape) - self._reinterpreted
        super().__init__(shape[:nb], shape[nb:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        lp = _v(self._base.log_prob(value))
        if self._reinterpreted:
            lp = jnp.sum(lp, axis=tuple(range(-self._reinterpreted, 0)))
        return _t(lp)

    def entropy(self):
        ent = _v(self._base.entropy())
        if self._reinterpreted:
            ent = jnp.sum(ent, axis=tuple(range(-self._reinterpreted, 0)))
        return _t(ent)


class TransformedDistribution(Distribution):
    """Pushforward of a base distribution through a chain of transforms
    (reference python/paddle/distribution/transformed_distribution.py:22)."""

    def __init__(self, base, transforms):
        from .transform import ChainTransform, Transform

        if isinstance(transforms, Transform):
            transforms = [transforms]
        self._base = base
        self._chain = ChainTransform(list(transforms))
        # batch shape is the base's; event shape follows the chain's shape map
        out = self._chain.forward_shape(base.batch_shape + base.event_shape)
        nb = len(base.batch_shape)
        super().__init__(base.batch_shape, tuple(out[nb:]))

    def rsample(self, shape=()):
        x = self._base.rsample(shape)
        return self._chain.forward(x)

    def sample(self, shape=()):
        s = self.rsample(shape)
        return _t(jax.lax.stop_gradient(_v(s)))

    def log_prob(self, value):
        y = _v(value)
        x = _v(self._chain.inverse(_t(y)))
        base_lp = _v(self._base.log_prob(_t(x)))
        ladj = _v(self._chain.forward_log_det_jacobian(_t(x)))
        # transforms with event_dim>0 already reduce their event dims; fold
        # any remaining trailing dims so the jacobian matches base_lp's rank
        if ladj.ndim > base_lp.ndim:
            ladj = jnp.sum(ladj, axis=tuple(range(base_lp.ndim - ladj.ndim, 0)))
        return _t(base_lp - ladj)
