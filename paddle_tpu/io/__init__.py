"""Data pipeline (reference: python/paddle/io/ — DataLoader at reader.py:216,
multiprocess workers in dataloader/dataloader_iter.py).

TPU-native design: the loader produces host numpy batches; device transfer is
a single jnp.asarray per batch (one H2D per step), and a background prefetch
thread keeps the host side ahead of the device — the role the reference's
shared-memory worker pool plays.  True multi-process decode can be layered on
(num_workers>0 uses a thread pool here; Python-level decode for vision is
rarely the bottleneck when XLA owns the step).
"""

from __future__ import annotations

import bisect
import itertools
import os
import math
import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = [
    "Dataset",
    "IterableDataset",
    "TensorDataset",
    "ComposeDataset",
    "ChainDataset",
    "ConcatDataset",
    "Subset",
    "random_split",
    "Sampler",
    "SequenceSampler",
    "RandomSampler",
    "WeightedRandomSampler",
    "SubsetRandomSampler",
    "BatchSampler",
    "DistributedBatchSampler",
    "DataLoader",
    "default_collate_fn",
    "get_worker_info",
    "InMemoryDataset",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0)
        return self.datasets[ds_idx][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        total = len(dataset)
        counts = [int(math.floor(total * f)) for f in lengths]
        rem = total - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, start = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[start : start + n].tolist()))
        start += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler).

    The shuffle stream is derived from (seed, epoch): per-epoch
    deterministic — every rank of a job agrees on the permutation — while
    two jobs with different base seeds see different shuffles (seeding from
    the epoch alone made every job shuffle identically)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False, seed=0):
        from paddle_tpu import distributed as dist

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.seed = int(seed)
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            # array seed: RandomState hashes both words, so (seed, epoch)
            # pairs never collide the way seed+epoch addition would
            rng = np.random.RandomState(
                np.array([self.seed, self.epoch], dtype=np.uint32)
            )
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch

    def state_dict(self):
        """Position-independent shuffle state: (seed, epoch) fully determine
        the permutation, so a resumed job rebuilds this epoch's index stream
        exactly (the DataLoader records how far into it the run got)."""
        return {"epoch": self.epoch, "seed": self.seed}

    def set_state_dict(self, state):
        self.epoch = int(state.get("epoch", self.epoch))
        self.seed = int(state.get("seed", self.seed))


class _WorkerInfo:
    def __init__(self, id=0, num_workers=1, dataset=None):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s._value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(group)) for group in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _shm_worker(ring_name, counter_path, ds_blob, batches, wid, nw, window):
    """Spawned DataLoader worker: fetch raw samples for a strided subset of
    batches and push pickled (batch_index, samples) items onto the shm ring.

    Runs in a fresh interpreter (spawn, not fork — forking a JAX-initialized
    multithreaded parent deadlocks), so the dataset arrives cloudpickled and
    nothing here may touch the JAX runtime."""
    import mmap
    import pickle
    import struct
    import time
    import traceback

    import cloudpickle

    from paddle_tpu import _native

    wring = None
    try:
        dataset = cloudpickle.loads(ds_blob)
        wring = _native.ShmRing(ring_name, create=False)
        fd = os.open(counter_path, os.O_RDONLY)
        try:
            consumed = mmap.mmap(fd, 8, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        n = len(batches)
        for k in range(wid, n, nw):
            # pace: never run more than `window` batches ahead of the parent
            while k - struct.unpack("Q", consumed[0:8])[0] >= window:
                time.sleep(0.002)
            samples = [dataset[i] for i in batches[k]]
            payload = pickle.dumps((k, samples), protocol=pickle.HIGHEST_PROTOCOL)
            wring.push(payload, timeout_ms=60_000)
    except BaseException:
        try:
            err = pickle.dumps((-1, (wid, traceback.format_exc())))
            if wring is not None:
                wring.push(err, timeout_ms=1000)
        except BaseException:
            pass
        os._exit(1)
    os._exit(0)


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=None,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
        prefetch_to_device=0,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        # use_shared_memory=True OPTS IN to spawned worker processes over the
        # native shm ring (reference default is shared memory; the default
        # None/False keeps the in-process thread path, which avoids the
        # per-epoch interpreter spawn cost when Python-level decode isn't
        # the bottleneck)
        self._use_shared_memory = bool(use_shared_memory)
        self.prefetch_factor = prefetch_factor
        # TPU-first input pipeline: stage the next N batches onto the device
        # asynchronously so host->HBM transfer overlaps the current step's
        # compute (jax dispatch is async; holding a window of device-resident
        # batches keeps the feed ahead of the MXU).  Reference analog:
        # use_buffer_reader's DoubleBuffer layer; 0 disables.
        self.prefetch_to_device = int(prefetch_to_device)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        # checkpoint/resume position (docs/CHECKPOINT.md): batches handed to
        # the caller this epoch, and how many to fast-forward past on the
        # next __iter__ after set_state_dict
        self._batches_yielded = 0
        self._resume_skip = 0
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no deterministic length")
        return len(self.batch_sampler)

    # ------------------------------------------------------ resume position
    def state_dict(self):
        """Mid-epoch position for exact resume: batches already handed out
        this epoch plus the sampler's (seed, epoch) when it exposes state
        (DistributedBatchSampler).  CheckpointManager persists this so a
        resumed run continues the SAME epoch stream where it stopped."""
        out = {"batches_yielded": self._batches_yielded}
        if self.batch_sampler is not None and hasattr(self.batch_sampler, "state_dict"):
            out["sampler"] = self.batch_sampler.state_dict()
        return out

    def set_state_dict(self, state):
        self._resume_skip = int(state.get("batches_yielded", 0))
        self._batches_yielded = self._resume_skip
        sampler_state = state.get("sampler")
        if sampler_state is not None and self.batch_sampler is not None \
                and hasattr(self.batch_sampler, "set_state_dict"):
            self.batch_sampler.set_state_dict(sampler_state)

    def _consume_resume_skip(self) -> int:
        skip, self._resume_skip = self._resume_skip, 0
        return skip

    def _index_batches(self):
        """Batch-sampler index stream, fast-forwarded past the resume skip.
        Skipping happens at the INDEX level — no sample is fetched or
        collated for skipped batches."""
        it = iter(self.batch_sampler)
        for _ in range(self._consume_resume_skip()):
            if next(it, None) is None:
                return
        yield from it

    def _iter_batches(self):
        if self._iterable_mode:
            # iterable datasets have no index space: fast-forward by
            # consuming raw samples (fetch cost paid, collate skipped)
            skip = self._consume_resume_skip()
            done = 0
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    if done < skip:
                        done += 1
                    else:
                        yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                if done >= skip:
                    yield self.collate_fn(batch)
            return
        if self.num_workers > 0:
            if self._use_shared_memory:
                from paddle_tpu import _native  # lazy: builds the .so on first use

                if _native.AVAILABLE:
                    yield from self._iter_mp_shm()
                    return
            # thread-pool fetch + bounded prefetch queue
            pool = ThreadPoolExecutor(max_workers=self.num_workers)
            try:
                futures = (
                    pool.submit(lambda idxs=idxs: self.collate_fn([self.dataset[i] for i in idxs]))
                    for idxs in self._index_batches()
                )
                window: list = []
                depth = self.num_workers * self.prefetch_factor
                for fut in futures:
                    window.append(fut)
                    if len(window) >= depth:
                        yield window.pop(0).result()
                for fut in window:
                    yield fut.result()
            finally:
                pool.shutdown(wait=False)
        else:
            for idxs in self._index_batches():
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_mp_shm(self):
        """True multi-process workers over the native shared-memory ring
        (reference: python/paddle/io/dataloader/dataloader_iter.py worker
        processes + shared-memory queues; ring in
        paddle_tpu/_native/src/shm_ring.cc).

        Workers are SPAWNED (never forked: the parent is a JAX-initialized
        multithreaded process, and fork there deadlocks) with the dataset
        shipped via cloudpickle; each fetches raw samples for its strided
        subset of batches and pushes pickled (batch_index, samples) items.
        The parent pops, reorders, runs collate_fn, and yields in sampler
        order — collate_fn runs in the PARENT so workers never touch the
        JAX/XLA runtime.  A file-backed consumed-counter paces workers to a
        bounded read-ahead window so the parent's reorder buffer cannot grow
        past ~nw * (prefetch_factor + 1) batches."""
        import mmap
        import multiprocessing as mp
        import pickle
        import struct
        import tempfile
        import uuid

        import cloudpickle

        from paddle_tpu import _native

        batches = list(self._index_batches())
        n = len(batches)
        if n == 0:
            return
        nw = min(self.num_workers, n)
        window = nw * (self.prefetch_factor + 1)
        uid = f"pt_dl_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        ring_name = "/" + uid
        ring = _native.ShmRing(ring_name, 128 << 20)
        # file-backed shared page: [0:8] = number of batches consumed by the
        # parent (a plain file under /dev/shm; mmap-shared with spawned
        # children by path, no resource-tracker involvement)
        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
        counter_path = os.path.join(shm_dir, uid + ".ctr")
        with open(counter_path, "wb") as f:
            f.write(struct.pack("Q", 0))
        fd = os.open(counter_path, os.O_RDWR)
        consumed = mmap.mmap(fd, 8)
        os.close(fd)
        consumed[0:8] = struct.pack("Q", 0)
        ds_blob = cloudpickle.dumps(self.dataset)
        ctx = mp.get_context("spawn")
        procs = []
        try:
            for wid in range(nw):
                p = ctx.Process(
                    target=_shm_worker,
                    args=(ring_name, counter_path, ds_blob, batches, wid, nw, window),
                    daemon=True,
                )
                p.start()
                procs.append(p)

            live = set(procs)
            holdback = {}
            next_k = 0
            while next_k < n:
                if next_k in holdback:
                    yield self.collate_fn(holdback.pop(next_k))
                    next_k += 1
                    consumed[0:8] = struct.pack("Q", next_k)
                    continue
                try:
                    payload = ring.pop(timeout_ms=1000)
                except TimeoutError:
                    # notice dead workers to turn hangs into failures
                    for p in list(live):
                        if not p.is_alive():
                            live.discard(p)
                            if p.exitcode != 0:
                                raise RuntimeError(
                                    "DataLoader worker died without reporting "
                                    "an exception"
                                ) from None
                    if not live:
                        raise RuntimeError(
                            f"DataLoader workers exited but only {next_k}/{n} "
                            "batches arrived"
                        ) from None
                    continue
                if payload is None:
                    raise RuntimeError("DataLoader ring closed early")
                k, samples = pickle.loads(payload)
                if k == -1:
                    wid, tb = samples
                    raise RuntimeError(
                        f"DataLoader worker {wid} raised:\n{tb}"
                    ) from None
                if k == next_k:
                    yield self.collate_fn(samples)
                    next_k += 1
                    consumed[0:8] = struct.pack("Q", next_k)
                else:
                    holdback[k] = samples
        finally:
            # close first so workers blocked in push() exit immediately;
            # advance the pacing counter so sleepers re-check and hit the
            # closed ring
            consumed[0:8] = struct.pack("Q", n + window)
            ring.close()
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=5)
            ring.destroy()
            consumed.close()
            try:
                os.unlink(counter_path)
            except OSError:
                pass

    def __iter__(self):
        if self.prefetch_to_device > 0:
            return self._count_yields(self._iter_device_prefetch())
        return self._count_yields(self._iter_batches())

    def _count_yields(self, inner):
        """Track the resume position: `_batches_yielded` counts batches the
        CALLER has received this epoch (bumped before the yield hands the
        batch out, so a checkpoint taken after the train step records the
        batch as consumed)."""
        self._batches_yielded = self._resume_skip
        for batch in inner:
            self._batches_yielded += 1
            yield batch

    def _iter_device_prefetch(self):
        import collections

        import jax
        import jax.numpy as jnp

        from paddle_tpu._core.tensor import Tensor

        def to_device(batch):
            def put(x):
                if isinstance(x, Tensor):
                    return Tensor(jnp.asarray(x._value), stop_gradient=x.stop_gradient)
                if isinstance(x, np.ndarray):
                    return Tensor(jnp.asarray(x))
                return x
            return jax.tree_util.tree_map(
                put, batch, is_leaf=lambda v: isinstance(v, (Tensor, np.ndarray))
            )

        window = collections.deque()
        for batch in self._iter_batches():
            window.append(to_device(batch))  # async dispatch: transfer starts now
            if len(window) > self.prefetch_to_device:
                yield window.popleft()
        while window:
            yield window.popleft()


class InMemoryDataset(Dataset):
    """paddle.distributed.InMemoryDataset lineage (reference
    paddle/fluid/framework/data_feed.cc + fleet/dataset/): loads the whole
    sample stream into host memory once, then supports global shuffle and
    epoch-wise iteration — the PS-mode feed.  TPU-native: samples live as a
    python list feeding the normal DataLoader; the protobuf feed/pipe
    readers collapse to a user-supplied parse function."""

    def __init__(self, parse_fn=None):
        self._samples = []
        self._parse = parse_fn

    def load_into_memory(self, files_or_samples):
        for item in files_or_samples:
            if isinstance(item, str):
                with open(item) as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if not line:
                            continue
                        self._samples.append(self._parse(line) if self._parse else line)
            else:
                self._samples.append(self._parse(item) if self._parse else item)
        return self

    def global_shuffle(self, seed=0):
        import random as _random

        _random.Random(seed).shuffle(self._samples)
        return self

    def release_memory(self):
        self._samples = []

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, idx):
        return self._samples[idx]


class QueueDataset(InMemoryDataset):
    """Streaming variant (reference: python/paddle/distributed/fleet/dataset
    QueueDataset): samples are consumed epoch-by-epoch from files without a
    global shuffle (single-pass queue semantics)."""

    def global_shuffle(self, seed=0):
        raise RuntimeError("QueueDataset is single-pass; use InMemoryDataset for global_shuffle")
