"""paddle.fft equivalent (reference: python/paddle/fft.py — 22 public
functions over phi pocketfft/cuFFT kernels).  On TPU the whole family maps
directly onto XLA's FFT HLO via jnp.fft; norm/axis/n semantics follow the
reference (numpy conventions)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("forward", "backward", "ortho")


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.fft(_v(x), n, axis, _norm(norm)))


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.ifft(_v(x), n, axis, _norm(norm)))


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.rfft(_v(x), n, axis, _norm(norm)))


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.irfft(_v(x), n, axis, _norm(norm)))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.hfft(_v(x), n, axis, _norm(norm)))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.ihfft(_v(x), n, axis, _norm(norm)))


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.fft2(_v(x), s, axes, _norm(norm)))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.ifft2(_v(x), s, axes, _norm(norm)))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.rfft2(_v(x), s, axes, _norm(norm)))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.irfft2(_v(x), s, axes, _norm(norm)))


def _swap_norm(norm):
    # hfft/ihfft are forward-like transforms built from irfft/rfft, so the
    # backward and forward normalizations trade places (same identity scipy
    # uses: hfftn(x) = irfftn(conj(x)) with swapped norm)
    return {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.fftn(_v(x), s, axes, _norm(norm)))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.ifftn(_v(x), s, axes, _norm(norm)))


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.rfftn(_v(x), s, axes, _norm(norm)))


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.irfftn(_v(x), s, axes, _norm(norm)))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    xc = _v(x)
    return Tensor(jnp.fft.irfftn(jnp.conj(xc), s, axes, _swap_norm(_norm(norm))))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    xc = _v(x)
    return Tensor(jnp.conj(jnp.fft.rfftn(xc, s, axes, _swap_norm(_norm(norm)))))


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d)
    return Tensor(out.astype(to_jax_dtype(dtype)) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d)
    return Tensor(out.astype(to_jax_dtype(dtype)) if dtype else out)


def fftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.fftshift(_v(x), axes))


def ifftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.ifftshift(_v(x), axes))
