"""Shared pallas helpers.

The framework runs jax with x64 enabled (paddle int64 semantics), which makes
bare python-int constants in BlockSpec index maps lower as i64 while traced
program ids are i32 — Mosaic rejects the mixed tuple.  `imap` wraps an index
map so every component is cast to int32.
"""

from __future__ import annotations

import jax.numpy as jnp


def imap(fn):
    def wrapped(*idx):
        out = fn(*idx)
        if not isinstance(out, tuple):
            out = (out,)
        return tuple(jnp.int32(v) for v in out)

    return wrapped
