"""Shared pallas helpers.

Mosaic requires every index-map component to be i32 (mixed-width index
tuples are rejected, and in this jax version a 64->32-bit convert inside
Mosaic lowering recurses forever).  `imap` wraps an index map so every
component is cast to int32; together with the framework-wide no-64-bit
policy (_core/dtype.py) this keeps kernel traces Mosaic-cleanly 32-bit —
enforced by the jaxpr scan in tests/test_ops_pallas.py.
"""

from __future__ import annotations

import jax.numpy as jnp


def imap(fn):
    def wrapped(*idx):
        out = fn(*idx)
        if not isinstance(out, tuple):
            out = (out,)
        return tuple(jnp.int32(v) for v in out)

    return wrapped
