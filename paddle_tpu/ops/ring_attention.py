"""Ring attention + Ulysses (all-to-all) attention for sequence/context
parallelism.

The reference's long-context support is the SEP axis (SURVEY.md §5: segment
parallel engine python/paddle/distributed/fleet/meta_parallel/segment_parallel.py,
no ring attention in the snapshot) — the TPU build exceeds it with real
sequence-parallel attention:

- `ring_attention`: blockwise online-softmax attention where K/V shards
  rotate around the SEP ring via `lax.ppermute` (ICI neighbor exchange),
  overlapping each hop with the local attention block — memory per chip is
  O(S/W), full causal semantics.  Differentiable end-to-end (ppermute's
  transpose is the reverse rotation; XLA schedules the collective-compute
  overlap).
- `ulysses_attention`: all-to-all head<->sequence reshard so each rank runs
  full-sequence attention on N/W heads with the Pallas flash kernel, then
  reshards back (DeepSpeed-Ulysses pattern on ICI).

Both are pure-jax functions meant to run inside shard_map with the SEP axis
in scope; q/k/v are the LOCAL sequence shards [B, S_local, N, H].
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.distributed.shard_map_compat import axis_size as _axis_size

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _local_block(q, k, v, scale, mode):
    """One q-shard x kv-chunk attention block in f32.

    q: [B, N, Sq, H]; k/v: [B, N, Sk, H]; mode: 'full' | 'causal' | 'skip'.
    Returns (numerator [B,N,Sq,H], row max m [B,N,Sq,1], row sum l [B,N,Sq,1]).
    """
    s = jnp.einsum("bnqh,bnkh->bnqk", q, k) * scale
    if mode == "causal":
        ql, kl = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # guard all-masked rows
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    num = jnp.einsum("bnqk,bnkh->bnqh", p, v)
    return num, m, l


def ring_attention(q, k, v, axis_name, *, causal=True, scale=None):
    """q/k/v: local shards [B, S_loc, N, H]; returns [B, S_loc, N, H].

    Sequence is sharded contiguously over `axis_name` (rank r owns rows
    [r*S_loc, (r+1)*S_loc)).  W-1 ppermute hops rotate the K/V shard left;
    online-softmax merge keeps full-precision statistics.
    """
    w = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B, N, S, H]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)

    b, n, s_loc, h = qt.shape
    acc = jnp.zeros((b, n, s_loc, h), jnp.float32)
    m_run = jnp.full((b, n, s_loc, 1), NEG_INF, jnp.float32)
    l_run = jnp.zeros((b, n, s_loc, 1), jnp.float32)

    perm = [(i, (i + 1) % w) for i in range(w)]  # rotate shards to the right

    def merge(carry, num, m_blk, l_blk, active):
        acc, m_run, l_run = carry
        m_new = jnp.maximum(m_run, m_blk)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m_blk - m_new)
        acc_new = acc * alpha + num * beta
        l_new = l_run * alpha + l_blk * beta
        keep = active.reshape(1, 1, 1, 1)
        return (
            jnp.where(keep, acc_new, acc),
            jnp.where(keep, m_new, m_run),
            jnp.where(keep, l_new, l_run),
        )

    kv = (kt, vt)
    carry = (acc, m_run, l_run)
    for step in range(w):
        src = (rank - step) % w  # which rank's shard we hold now
        kc, vc = kv
        if causal:
            # diagonal: causal-mask; below diagonal (src < rank): full; above: skip
            num_c, m_c, l_c = _local_block(qt, kc, vc, scale, "causal")
            num_f, m_f, l_f = _local_block(qt, kc, vc, scale, "full")
            is_diag = src == rank
            num = jnp.where(is_diag, num_c, num_f)
            m_blk = jnp.where(is_diag, m_c, m_f)
            l_blk = jnp.where(is_diag, l_c, l_f)
            active = src <= rank
        else:
            num, m_blk, l_blk = _local_block(qt, kc, vc, scale, "full")
            active = jnp.bool_(True)
        carry = merge(carry, num, m_blk, l_blk, active)
        if step + 1 < w:
            kv = (
                lax.ppermute(kv[0], axis_name, perm),
                lax.ppermute(kv[1], axis_name, perm),
            )

    acc, m_run, l_run = carry
    l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
    out = acc / l_safe
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, *, causal=True, scale=None):
    """DeepSpeed-Ulysses: all-to-all seq<->heads, local full-seq flash
    attention, all-to-all back.  Heads must divide the axis size.
    q/k/v: [B, S_loc, N, H] -> returns same."""
    w = _axis_size(axis_name)
    b, s_loc, n, h = q.shape
    assert n % w == 0, "num heads must be divisible by sep degree for ulysses"

    def seq_to_heads(x):
        # [B, S_loc, N, H] -> [B, W*S_loc, N/W, H]: split heads, gather seq
        x = x.reshape(b, s_loc, w, n // w, h)
        x = jnp.moveaxis(x, 2, 0)  # [W, B, S_loc, N/W, H]
        x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=False)
        # leading axis now indexes seq chunks in ring order
        x = jnp.moveaxis(x, 0, 1)  # [B, W, S_loc, N/W, H]
        return x.reshape(b, w * s_loc, n // w, h)

    def heads_to_seq(x):
        x = x.reshape(b, w, s_loc, n // w, h)
        x = jnp.moveaxis(x, 1, 0)
        x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=False)
        x = jnp.moveaxis(x, 0, 2)  # [B, S_loc, W, N/W, H]
        return x.reshape(b, s_loc, n, h)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    from paddle_tpu.ops import use_pallas
    from paddle_tpu.ops.flash_attention import flash_attention, flash_attention_reference

    fn = flash_attention if use_pallas() else flash_attention_reference
    out = fn(qg, kg, vg, causal=causal, scale=scale)
    return heads_to_seq(out)
