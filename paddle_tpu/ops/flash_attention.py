"""Flash attention as a Pallas TPU kernel.

Capability parity with the reference's flash-attention integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu + python wrapper
paddle.nn.functional.flash_attention) but implemented TPU-first: blockwise
online-softmax attention tiled for the MXU, Q/K/V blocks staged through VMEM
by the Pallas pipeline, fp32 accumulation, logsumexp saved for the backward.

Layout convention: public entry takes Paddle's [B, S, N, H]; kernels run in
[B, N, S, H].  GQA (num_kv_heads < num_heads) is handled in the forward with a
BlockSpec index map (no materialized repeat); the backward materializes the
repeat and reduces dK/dV over the head group.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops._pl_utils import imap
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _mask_val():
    # Explicit f32: under global x64 a bare Python float becomes an f64
    # constant inside the kernel trace, which Mosaic cannot lower (infinite
    # recursion in its f64->f32 conversion helper).  tests/test_ops_pallas.py
    # scans every kernel jaxpr for 64-bit types to keep this class of bug out.
    return jnp.float32(DEFAULT_MASK_VALUE)


def _block_sizes(seq_q, seq_k, head_dim=128, dtype=None, causal=False):
    """Tile selection, in precedence order (reference
    phi/kernels/autotune/cache.h consults its config cache the same way):

    1. explicit FLAGS_flash_block_q/_k override — invalid values WARN
       loudly and fall through (VERDICT r3 #10: no silent fallbacks);
    2. the per-device-kind autotune cache (ops/autotune.py) for this
       (seq, head_dim, dtype, causal) signature;
    3. the 128x128 default (measured best on v5e at the flagship shapes).
    """
    import warnings

    from paddle_tpu._core import flags as _flags
    from paddle_tpu.ops import autotune as _at

    def _fallback(seq):
        return min(128, seq)

    # 1. explicit flags
    fq, fk = int(_flags.flag("FLAGS_flash_block_q")), int(_flags.flag("FLAGS_flash_block_k"))
    if fq > 0 or fk > 0:
        bq = min(fq, seq_q) if fq > 0 else _fallback(seq_q)
        bk = min(fk, seq_k) if fk > 0 else _fallback(seq_k)
        reason = _at.validate_flash_tile(bq, bk, seq_q, seq_k, head_dim)
        if reason is None:
            return bq, bk
        warnings.warn(
            f"flash_attention: FLAGS_flash_block_q/_k=({fq},{fk}) invalid "
            f"for seq=({seq_q},{seq_k}), head_dim={head_dim}: {reason}; "
            "using the autotune cache / 128x128 default instead",
            stacklevel=3,
        )

    # 2. autotune cache
    key = {"seq_q": seq_q, "seq_k": seq_k, "head_dim": head_dim,
           "dtype": jnp.dtype(dtype).name if dtype is not None else "bfloat16",
           "causal": bool(causal)}
    tuned = _at.lookup("flash_fwd", key)
    if tuned:
        bq, bk = int(tuned["block_q"]), int(tuned["block_k"])
        reason = _at.validate_flash_tile(bq, bk, seq_q, seq_k, head_dim)
        if reason is None:
            return bq, bk
        warnings.warn(
            f"flash_attention: cached tile ({bq},{bk}) for {key} is invalid "
            f"on this device: {reason}; using the 128x128 default "
            "(re-run `python -m paddle_tpu.ops.autotune`)",
            stacklevel=3,
        )

    # 3. default
    return _fallback(seq_q), _fallback(seq_k)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k):
    # q_ref: [bq, H]; k_ref/v_ref: [S, H]; o_ref: [bq, H]; lse_ref: [bq, 128]
    bq, head_dim = q_ref.shape
    seq_k = k_ref.shape[0]
    qi = pl.program_id(2)  # q-block index
    q = q_ref[:].astype(jnp.float32) * jnp.float32(scale)

    num_kv = seq_k // block_k
    # bottom-right causal alignment for Sq != Sk (the kv-cache/decode
    # convention; matches flash_attention_reference's tril(k=Sk-Sq))
    q_off = seq_k - pl.num_programs(2) * bq
    if causal:
        # only kv blocks whose start <= last (aligned) q row
        num_kv_dyn = (jnp.int32((qi + 1) * bq + q_off + block_k - 1)
                      // jnp.int32(block_k))
        num_kv_dyn = jnp.minimum(num_kv_dyn, num_kv)
    else:
        num_kv_dyn = jnp.int32(num_kv)

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            q_pos = q_off + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _mask_val())
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, head_dim), jnp.float32)
    m0 = jnp.full((bq, 1), DEFAULT_MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(jnp.int32(0), num_kv_dyn, body, (acc0, m0, l0))

    l_safe = jnp.where(l == 0.0, jnp.float32(1.0), l)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse = (m + jnp.log(l_safe)).astype(jnp.float32)  # [bq, 1]
    lse_ref[:] = jnp.broadcast_to(lse, lse_ref.shape)


def _fwd(q, k, v, scale, causal, block_q, block_k):
    # q: [B, N, Sq, H]; k/v: [B, Nkv, Sk, H]
    batch, num_heads, seq_q, head_dim = q.shape
    num_kv_heads, seq_k = k.shape[1], k.shape[2]
    group = num_heads // num_kv_heads
    grid = (batch, num_heads, seq_q // block_q)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, head_dim), imap(lambda b, n, i: (b, n, i, 0))),
            pl.BlockSpec((None, None, seq_k, head_dim), imap(lambda b, n, i: (b, n // group, 0, 0))),
            pl.BlockSpec((None, None, seq_k, head_dim), imap(lambda b, n, i: (b, n // group, 0, 0))),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, head_dim), imap(lambda b, n, i: (b, n, i, 0))),
            pl.BlockSpec((None, None, block_q, 128), imap(lambda b, n, i: (b, n, i, 0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, num_heads, seq_q, 128), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(q, k, v)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, causal, block_k):
    bq, head_dim = q_ref.shape
    seq_k = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, :1]  # [bq, 1]
    delta = delta_ref[:, :1]  # [bq, 1]
    scale = jnp.float32(scale)

    num_kv = seq_k // block_k
    q_off = seq_k - pl.num_programs(2) * bq  # bottom-right alignment
    if causal:
        num_kv_dyn = jnp.minimum(
            jnp.int32((qi + 1) * bq + q_off + block_k - 1) // jnp.int32(block_k),
            num_kv)
    else:
        num_kv_dyn = jnp.int32(num_kv)

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_off + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _mask_val())
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(jnp.int32(0), num_kv_dyn, body, jnp.zeros((bq, head_dim), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, causal, block_q):
    bk, head_dim = k_ref.shape
    seq_q = q_ref.shape[0]
    ki = pl.program_id(2)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    scale = jnp.float32(scale)

    num_q = seq_q // block_q
    q_off = pl.num_programs(2) * bk - seq_q  # bottom-right alignment
    if causal:
        # q blocks whose last aligned row precedes this kv block start
        # contribute nothing
        start_q = jnp.maximum(jnp.int32(ki * bk) - jnp.int32(q_off),
                              jnp.int32(0)) // jnp.int32(block_q)
    else:
        start_q = jnp.int32(0)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :1]
        delta = delta_ref[pl.ds(i * block_q, block_q), :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_off + i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _mask_val())
        p = jnp.exp(s - lse)  # [bq_blk, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, head_dim), jnp.float32)
    dv0 = jnp.zeros((bk, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, jnp.int32(num_q), body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k):
    batch, num_heads, seq_q, head_dim = q.shape
    num_kv_heads, seq_k = k.shape[1], k.shape[2]
    group = num_heads // num_kv_heads
    if group > 1:
        k_rep = jnp.repeat(k, group, axis=1)
        v_rep = jnp.repeat(v, group, axis=1)
    else:
        k_rep, v_rep = k, v

    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)  # [B,N,Sq]
    lse_b = jnp.broadcast_to(lse[..., None], (*lse.shape, 128)).astype(jnp.float32)
    delta_b = jnp.broadcast_to(delta[..., None], (*delta.shape, 128)).astype(jnp.float32)
    interpret = jax.default_backend() != "tpu"

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, block_k=block_k),
        grid=(batch, num_heads, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, head_dim), imap(lambda b, n, i: (b, n, i, 0))),
            pl.BlockSpec((None, None, seq_k, head_dim), imap(lambda b, n, i: (b, n, 0, 0))),
            pl.BlockSpec((None, None, seq_k, head_dim), imap(lambda b, n, i: (b, n, 0, 0))),
            pl.BlockSpec((None, None, block_q, head_dim), imap(lambda b, n, i: (b, n, i, 0))),
            pl.BlockSpec((None, None, block_q, 128), imap(lambda b, n, i: (b, n, i, 0))),
            pl.BlockSpec((None, None, block_q, 128), imap(lambda b, n, i: (b, n, i, 0))),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, head_dim), imap(lambda b, n, i: (b, n, i, 0))),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k_rep, v_rep, do, lse_b, delta_b)

    dk_rep, dv_rep = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q),
        grid=(batch, num_heads, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, None, seq_q, head_dim), imap(lambda b, n, j: (b, n, 0, 0))),
            pl.BlockSpec((None, None, block_k, head_dim), imap(lambda b, n, j: (b, n, j, 0))),
            pl.BlockSpec((None, None, block_k, head_dim), imap(lambda b, n, j: (b, n, j, 0))),
            pl.BlockSpec((None, None, seq_q, head_dim), imap(lambda b, n, j: (b, n, 0, 0))),
            pl.BlockSpec((None, None, seq_q, 128), imap(lambda b, n, j: (b, n, 0, 0))),
            pl.BlockSpec((None, None, seq_q, 128), imap(lambda b, n, j: (b, n, 0, 0))),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, head_dim), imap(lambda b, n, j: (b, n, j, 0))),
            pl.BlockSpec((None, None, block_k, head_dim), imap(lambda b, n, j: (b, n, j, 0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k_rep.shape, k.dtype),
            jax.ShapeDtypeStruct(v_rep.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k_rep, v_rep, do, lse_b, delta_b)

    if group > 1:
        dk = dk_rep.reshape(batch, num_kv_heads, group, seq_k, head_dim).sum(axis=2).astype(k.dtype)
        dv = dv_rep.reshape(batch, num_kv_heads, group, seq_k, head_dim).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_rep, dv_rep
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper (operates in [B, N, S, H])
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bnsh(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k)


_flash_bnsh.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x, pad


def flash_attention(q, k, v, *, causal=False, scale=None):
    """Blockwise flash attention.  q/k/v: [B, S, N, H] (paddle layout).

    Non-multiple-of-block sequence lengths are zero-padded; for the non-causal
    case padded keys are masked out by construction only when causal — so for
    safety arbitrary lengths take the padded-causal path or mask via the
    reference; practical training shapes are multiples of the block size.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    seq_q, seq_k = qt.shape[2], kt.shape[2]
    block_q, block_k = _block_sizes(
        seq_q, seq_k, head_dim=qt.shape[-1], dtype=qt.dtype, causal=causal)
    if seq_q % block_q or seq_k % block_k:
        # padding keys changes non-causal softmax; fall back to the full
        # O(S^2)-memory reference — fine for tests, a cliff in real use
        import warnings

        warnings.warn(
            f"flash_attention: seq lengths ({seq_q}, {seq_k}) are not "
            f"multiples of the ({block_q}, {block_k}) block; falling back to "
            "full-softmax attention (O(S^2) memory). Pad sequences to a "
            "multiple of 128 for the Pallas kernel.",
            stacklevel=2,
        )
        return flash_attention_reference(q, k, v, causal=causal, scale=scale)
    out = _flash_bnsh(qt, kt, vt, float(scale), bool(causal), block_q, block_k)
    return jnp.swapaxes(out, 1, 2)


def flash_attention_reference(q, k, v, *, causal=False, scale=None):
    """Pure-jnp oracle with identical semantics ([B, S, N, H] layout)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    group = qt.shape[1] // kt.shape[1]
    if group > 1:
        kt = jnp.repeat(kt, group, axis=1)
        vt = jnp.repeat(vt, group, axis=1)
    logits = jnp.einsum("bnqh,bnkh->bnqk", qt, kt) * scale
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnqk,bnkh->bnqh", probs, vt)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
