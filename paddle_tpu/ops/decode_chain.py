"""Searchable fused decode hot chain: paged gather → dequant → sdpa core →
(running-max) quant-write as ONE Pallas dispatch per layer per token.

Schedule search, phase 2 (ROADMAP item 4; docs/SCHEDULE_SEARCH.md).  The
decode macro-step's per-token chain runs today as separate XLA ops inside
the jitted scan body — exactly the memory-bound fusion-miss class
"Operator Fusion in XLA" (arXiv 2301.13062) catalogs.  This module makes
that chain a SEARCHABLE subgraph for static/schedule_search.py's
ScheduleSearcher: `DecodeChainSpec` describes the chain at one engine
geometry and implements the same searcher protocol Program subgraphs use
(enumerate → roofline → VMEM → parity → measure → measured-win gate), so
winners and losers persist per device kind under the `schedule/decode_*`
AutotuneCache namespaces and the engine's compiled macro-step consumes an
accepted config with zero re-measurement (serving._resolve_decode_chain).

Semantics are NEVER trusted to the gate: every candidate must pass a
numerics parity check against the XLA twin BEFORE it may be measured
(`check_parity`), with the same contract the engine's stream tests
enforce — full-precision ('bf16') pools bit-exact, int8 pools bit-exact
on the quantized payload/scales with the attention output inside the
PR-6 drift budget.  That is why the default `batch` layout replays the
EXACT unfused ops (paged_write / paged_gather / gathered_attention — one
definition each, imported from ops.paged_attention) inside one
pallas_call: fusion changes the number of HBM round trips, never the
math.  The int8-only `rows` layout grids over batch rows (smaller VMEM
working set, whole-pool re-staging per row in the traffic model) and is
tolerance-gated on the attention output.

Mixed-dtype roofline honesty: a QuantPool chain moves int8 payload bytes
AND float32 scale bytes — `traffic_bytes` costs every pool leaf at its
OWN itemsize instead of assuming one dtype for the whole subgraph (the
bf16-pool chain at identical geometry models ~2x the gather traffic,
which is the int8 capacity story told by the cost model).

CPU/on-chip honesty: kernels run in Pallas interpret mode off-TPU, where
XLA usually wins and the gate (correctly) disables — tests and the bench
--smoke twin decide through schedule_search.measure_override.  On TPU the
whole-pool VMEM residency of these layouts is validated by
ops.autotune.validate_tile, so geometries whose pools exceed the budget
are pruned honestly rather than faked; a DMA-pipelined variant can join
the candidate space later without changing the search contract.

Mesh-sharded chains (schedule search over the mesh; ROADMAP item 3): a
spec built with ``mesh=`` describes the SAME chain on a TP-sharded
engine.  ``build`` then wraps the single-device kernel in ``shard_map``
over the engine's pool layout — pools P(None, mp) on the KV-head dim,
q/k_new/v_new P(None, mp, None) on the head dim, tables/lens replicated —
with the per-device kernel geometry taken from
``NamedSharding.shard_shape`` (the same source the serving telemetry's
``pool_device_nbytes`` uses).  GQA head contiguity makes every candidate
layout head-local: device d's query-head shard [d·n/mp, (d+1)·n/mp)
attends exactly its own kv-head shard (``gathered_attention`` repeats kv
heads in contiguous groups), so the fused chain runs ZERO in-kernel
collectives and the mesh adds NO drift — parity re-gates bit-exactly
against the sharded XLA twin (synthetic args committed to the engine's
NamedShardings, reference jitted under GSPMD), the PR-11 contract.  The
roofline costs PER-DEVICE traffic plus ``collective_bytes`` — the psum an
attention epilogue would need if a kv group ever split across devices (0
for every current layout; o_proj's row-parallel psum lives OUTSIDE the
chain, in GSPMD's hands).  Cache verdicts are keyed by (device kind,
mesh shape): the AutotuneCache file is per device kind and ``key()``
gains a ``mesh`` entry only when a mesh is set, so single-device and
sharded verdicts never collide (tested by the cache-pollution
regression).  ``static.mesh_lint.lint_decode_chain`` statically checks
the built kernel's collectives before an engine adopts it.

``PrefillChainSpec`` extends the same searcher protocol to the OTHER
serving hot path: the chunked-prefill attention core (q chunk against
the growing cache, bottom-right aligned).  Candidates tile query rows
(bit-exact — softmax is per row) and stage K/V in chunks (pure data
movement), so long-prompt pours stop being a pure XLA chain once a
config wins; models/llama adopts through ``fused_prefill_attention``
under ``prefill_chain_scope``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

__all__ = [
    "DecodeChainSpec",
    "PrefillChainSpec",
    "spec_from_arrays",
    "ensure_decision",
    "fused_decode_step",
    "fused_prefill_attention",
]

# per-copy-step turnaround for the analytic ranking (the scale of one DMA
# issue): breaks ties between gather granularities whose traffic is
# identical, the same role schedule_search._GRID_STEP_OVERHEAD_S plays
# for 1-D grids
_COPY_STEP_OVERHEAD_S = 1e-7


@dataclass
class DecodeChainSpec:
    """One engine geometry's decode hot chain, ready to schedule.

    kv: 'bf16' (full-precision pools in `dtype`) | 'int8' (QuantPool —
    int8 payload + per-block-per-head f32 scales, running-max writes).
    num_blocks counts the WHOLE pool incl. scratch pages; max_blocks is
    the per-sequence block-table width.

    mesh: None for the single-device chain, or the engine's ProcessMesh —
    the spec then describes the TP-sharded chain (pools on the KV-head
    dim over `mp_axis`, the serving layout) and builds inside shard_map;
    the mesh handle itself never enters `key()` (only its shape string
    does), so cache entries stay (device kind, mesh shape)-keyed and
    host-portable."""

    batch: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    block_size: int
    max_blocks: int
    num_blocks: int
    kv: str = "bf16"
    dtype: object = np.float32
    mesh: object = None
    mp_axis: str = "mp"

    check_parity = True  # searcher protocol: candidates numerics-gate

    def __post_init__(self):
        if self.kv not in ("bf16", "int8"):
            raise ValueError(f"kv must be 'bf16' or 'int8', got {self.kv!r}")

    # ------------------------------------------------------------ identity
    @property
    def seq(self) -> int:
        return self.max_blocks * self.block_size

    def kernel_name(self) -> str:
        return f"schedule/decode_{self.kv}"

    def key(self) -> dict:
        k = {
            "b": self.batch,
            "n": self.num_heads,
            "nkv": self.num_kv_heads,
            "h": self.head_dim,
            "bs": self.block_size,
            "w": self.max_blocks,
            "nb": self.num_blocks,
            "dtype": np.dtype(self.dtype).name,
        }
        # (device kind, mesh shape) verdict keying: the AutotuneCache file
        # is already per device kind; the mesh-shape entry — ONLY when a
        # mesh is set, so existing single-device key strings stay stable —
        # keeps single-device and sharded verdicts from ever colliding
        if self.mesh is not None:
            k["mesh"] = self.mesh_desc()
        return k

    # ---------------------------------------------------------- mesh view
    def mesh_desc(self) -> str:
        """'mp2'-style mesh shape string (the serving telemetry format)."""
        if self.mesh is None:
            return ""
        return "x".join(f"{n}{s}" for n, s in zip(self.mesh.dim_names,
                                                  self.mesh.shape))

    def _mp(self) -> int:
        return int(dict(zip(self.mesh.dim_names,
                            self.mesh.shape))[self.mp_axis])

    def _shardings(self):
        """(pool, heads, replicated) NamedShardings of the serving layout:
        pools shard the KV-head dim (axis 1 of every pool leaf — payload
        AND scales), q/k_new/v_new shard the head dim, tables/lens ride
        replicated."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        jm = self.mesh.jax_mesh
        return (NamedSharding(jm, P(None, self.mp_axis)),
                NamedSharding(jm, P(None, self.mp_axis, None)),
                NamedSharding(jm, P()))

    def device_spec(self) -> "DecodeChainSpec":
        """The PER-DEVICE replica of this geometry: head counts come from
        ``NamedSharding.shard_shape`` over the committed pool/head layouts
        — the same source ops.paged_attention.pool_device_nbytes uses for
        the telemetry's per-device bytes — never from ad-hoc division."""
        import dataclasses

        pool_s, head_s, _rep = self._shardings()
        pool_shape = (self.num_blocks, self.num_kv_heads, self.block_size,
                      self.head_dim)
        _nb, nkv_local, _bs, _h = pool_s.shard_shape(pool_shape)
        _b, n_local, _h2 = head_s.shard_shape(
            (self.batch, self.num_heads, self.head_dim))
        return dataclasses.replace(self, mesh=None,
                                   num_heads=int(n_local),
                                   num_kv_heads=int(nkv_local))

    def label(self) -> str:
        from paddle_tpu.ops.autotune import _key_str

        return f"{self.kernel_name()}|{_key_str(self.key())}"

    def config_label(self, config) -> str:
        lbl = f"#{config.get('layout', 'batch')}-{config.get('gather', 'take')}"
        if config.get("gather") == "loop":
            lbl += f"u{config.get('unroll', 1)}"
        return lbl

    # ------------------------------------------------------ candidate space
    def enumerate_configs(self):
        """Schedule space: `layout` — 'batch' replays the whole batch in
        one grid step (bit-exact by construction; the only layout a
        'bf16' chain may use), 'rows' (int8 only) grids over batch rows;
        `gather` — 'take' stages pages in one bulk gather, 'loop' copies
        `unroll` pages per step (the DMA granularity knob; values are
        bit-identical either way — gathering is pure data movement)."""
        unrolls = [u for u in (1, 2, 4)
                   if u <= self.max_blocks and self.max_blocks % u == 0]
        layouts = ["batch"] + (["rows"] if self.kv == "int8" else [])
        out = []
        for layout in layouts:
            out.append({"layout": layout, "gather": "take"})
            for u in unrolls:
                out.append({"layout": layout, "gather": "loop", "unroll": u})
        return out

    # ------------------------------------------------------------ cost model
    def _leaf_bytes(self):
        """[(name, nbytes)] per pool LEAF at its OWN dtype — one pool's
        int8 payload and f32 scales are costed separately (the mixed-dtype
        fix: a QuantPool chain is not 'one dtype' to the roofline)."""
        nb, nkv, bs, h = (self.num_blocks, self.num_kv_heads,
                          self.block_size, self.head_dim)
        if self.kv == "int8":
            return [("payload", nb * nkv * bs * h * 1),
                    ("scale", nb * nkv * 4)]
        return [("payload", nb * nkv * bs * h
                 * np.dtype(self.dtype).itemsize)]

    def _write_bytes(self):
        """HBM bytes the chain's write phase touches, per pool: bf16
        writes one token slot per row; int8 rewrites each touched block
        (running-max rescale) plus its f32 scales."""
        b, nkv, bs, h = (self.batch, self.num_kv_heads, self.block_size,
                         self.head_dim)
        if self.kv == "int8":
            return b * nkv * bs * h * 1 + b * nkv * 4
        return b * nkv * h * np.dtype(self.dtype).itemsize

    def collective_bytes(self, config) -> int:
        """ICI bytes of the psum the attention epilogue needs, per device.
        Every current layout is head-local — P(None, mp) keeps each query
        head's whole GQA kv group on its own device (contiguous repeat in
        gathered_attention), so the chain runs zero in-kernel collectives
        and this is 0; o_proj's row-parallel psum stays OUTSIDE the chain
        (GSPMD's epilogue, costed by the step program, not the kernel).
        A future layout that splits a kv group across devices must cost
        its partial-output psum here: one [b, n_local, h] f32 reduction."""
        if self.mesh is None:
            return 0
        mp = self._mp()
        if self.num_heads % mp == 0 and self.num_kv_heads % mp == 0:
            return 0  # head-local: no epilogue reduction
        # non-divisible heads can't ride shard_shape (uneven split):
        # cost the ceil-divided local head count directly — build()
        # refuses these geometries anyway, this is the honest estimate
        n_local = -(-self.num_heads // mp)
        return self.batch * n_local * self.head_dim * 4

    def traffic_bytes(self, config) -> int:
        """Modeled HBM traffic: every pool leaf read at its own itemsize
        (once for the 'batch' layout; re-staged per row — x batch — for
        'rows'), the write phase's touched bytes, and the q/k/v/token
        tensors + output once.  A mesh spec reports the PER-DEVICE number
        — the device_spec's traffic (shard_shape-divided pools/heads)
        plus the epilogue's collective bytes — because per-device time is
        what the roofline ranks against the sharded XLA twin."""
        if self.mesh is not None:
            return (self.device_spec().traffic_bytes(config)
                    + self.collective_bytes(config))
        it = np.dtype(self.dtype).itemsize
        b, n, nkv, h = (self.batch, self.num_heads, self.num_kv_heads,
                        self.head_dim)
        read_factor = b if config.get("layout") == "rows" else 1
        pool_reads = 2 * sum(sz for _name, sz in self._leaf_bytes())
        traffic = pool_reads * read_factor
        traffic += 2 * self._write_bytes()
        traffic += b * n * h * it            # q
        traffic += 2 * b * nkv * h * it      # k_new, v_new
        traffic += b * self.max_blocks * 4 + b * 4  # tables, lens
        traffic += b * n * h * it            # attention output
        return int(traffic)

    def flops(self) -> float:
        if self.mesh is not None:  # per-device: heads divide over the mesh
            return self.device_spec().flops()
        b, n, h, s = self.batch, self.num_heads, self.head_dim, self.seq
        return 4.0 * b * n * s * h + 5.0 * b * n * s

    def roofline_ms(self, config, cost_model=None) -> float:
        """Analytic rank: per-device flops over per-device traffic (which
        already includes the epilogue's collective bytes on mesh specs),
        plus the copy-granularity tie-breaker and — when a layout needs
        an epilogue psum at all — one collective-launch turnaround."""
        if cost_model is None:
            from paddle_tpu.cost_model import OpCostModel

            cost_model = OpCostModel()
        if config.get("gather") == "loop":
            u = int(config.get("unroll", 1) or 1)
            # one copy per page group per row per pool
            copies = 2 * self.batch * (self.max_blocks // u)
        else:
            copies = 2  # one bulk gather per pool
        if self.collective_bytes(config):
            copies += 1  # the psum launch rides the same turnaround scale
        return (cost_model.flops_time(self.flops(),
                                      self.traffic_bytes(config))
                + copies * _COPY_STEP_OVERHEAD_S) * 1e3

    def vmem_bytes(self, config) -> int:
        """f32-staged working set per grid step (double-buffered, the
        validate_tile convention): the resident pool leaves plus the
        per-step gathered views, logits tile, and token blocks.  The
        'rows' layout holds one row's views; both layouts keep the whole
        pool resident — on-chip geometries whose pools exceed VMEM are
        pruned honestly here.  A mesh spec reports its device_spec's
        working set: VMEM is a per-chip budget."""
        if self.mesh is not None:
            return self.device_spec().vmem_bytes(config)
        it = np.dtype(self.dtype).itemsize
        rows = 1 if config.get("layout") == "rows" else self.batch
        n, nkv, h, s = (self.num_heads, self.num_kv_heads, self.head_dim,
                        self.seq)
        total = 2 * sum(sz for _name, sz in self._leaf_bytes())  # pools
        total += 2 * rows * nkv * s * h * 4        # gathered k/v (f32)
        total += rows * n * s * 4                  # logits tile
        total += rows * (n + 2 * nkv) * h * it     # q, k_new, v_new
        total += rows * n * h * it                 # output block
        return int(total) * 2

    # ------------------------------------------------------------- numerics
    def reference(self):
        """The XLA twin: EXACTLY the unfused macro-step sequence
        (models/llama._decode_layer_paged lines write→write→attend)."""
        from paddle_tpu.ops import paged_attention as pa

        def ref(kc, vc, q, kn, vn, tables, lens):
            pos = lens - 1
            kc = pa.paged_write(kc, kn, tables, pos)
            vc = pa.paged_write(vc, vn, tables, pos)
            o = pa.paged_decode_attention(q, kc, vc, tables, lens)
            return o, kc, vc

        return ref

    def synthetic_args(self):
        """Deterministic engine-shaped args: every row owns DISJOINT
        pool blocks (the engine's allocator invariant the 'rows' layout
        relies on) poured with random content, lengths spread over the
        table span."""
        import jax.numpy as jnp

        from paddle_tpu.ops import paged_attention as pa

        b, n, nkv, h = (self.batch, self.num_heads, self.num_kv_heads,
                        self.head_dim)
        bs, w = self.block_size, self.max_blocks
        rng = np.random.default_rng(0)
        dt = jnp.dtype(self.dtype)
        kc, vc = pa.alloc_paged_cache(
            self.num_blocks, nkv, bs, h,
            jnp.int8 if self.kv == "int8" else dt)
        ids = np.arange(b * w, dtype=np.int32).reshape(b, w)
        kv = jnp.asarray(rng.standard_normal((b * w, nkv, bs, h)),
                         jnp.float32)
        vv = jnp.asarray(rng.standard_normal((b * w, nkv, bs, h)),
                         jnp.float32)
        kc = pa.paged_pour_blocks(kc, kv, ids.reshape(-1))
        vc = pa.paged_pour_blocks(vc, vv, ids.reshape(-1))
        s = self.seq
        lens = np.clip(np.linspace(2, s, b).astype(np.int32), 2, s)
        args = (kc, vc,
                jnp.asarray(rng.standard_normal((b, n, h)), dt),
                jnp.asarray(rng.standard_normal((b, nkv, h)), dt),
                jnp.asarray(rng.standard_normal((b, nkv, h)), dt),
                jnp.asarray(ids), jnp.asarray(lens))
        if self.mesh is None:
            return args
        # commit the args to the engine's committed layout, so jitting
        # reference() over them IS the sharded XLA twin (GSPMD partitions
        # the unfused ops exactly as the serving step does) and the parity
        # gate proves the mesh adds NO drift — the PR-11 contract
        import jax

        pool_s, head_s, rep = self._shardings()
        kc, vc, q, kn, vn, tables, lens = args
        return (jax.device_put(kc, pool_s), jax.device_put(vc, pool_s),
                jax.device_put(q, head_s), jax.device_put(kn, head_s),
                jax.device_put(vn, head_s),
                jax.device_put(tables, rep), jax.device_put(lens, rep))

    def parity_ok(self, fn, args, reference_out) -> bool:
        """The parity gate: pools must match the twin BIT-EXACTLY for
        both kv kinds (quantized writes are deterministic integer math);
        the attention output must be bit-exact for 'bf16' and inside the
        documented PR-6 drift budget for 'int8' (the 'rows' layout
        re-associates the per-row einsum)."""
        import jax

        try:
            got = fn(*args)
        except Exception:
            return False
        r_leaves = jax.tree_util.tree_leaves(reference_out)
        g_leaves = jax.tree_util.tree_leaves(got)
        if len(r_leaves) != len(g_leaves):
            return False
        for i, (r, g) in enumerate(zip(r_leaves, g_leaves)):
            if r.shape != g.shape or r.dtype != g.dtype:
                return False
            if i == 0 and self.kv == "int8":  # attention output leaf
                if not np.allclose(np.asarray(r, np.float32),
                                   np.asarray(g, np.float32),
                                   rtol=1e-3, atol=1e-4):
                    return False
            elif not bool((r == g).all()):
                return False
        return True

    # --------------------------------------------------------------- build
    def build(self, config):
        if config.get("layout") == "rows" and self.kv != "int8":
            raise ValueError(
                "the per-row layout re-associates the attention "
                "einsum: bf16 chains are bit-exact-only ('batch')")
        if self.mesh is not None:
            return _build_sharded(self, config)
        if config.get("layout") == "rows":
            return _build_rows(self, config)
        return _build_batch(self, config)


def _loop_gather(pool, tables, unroll):
    """paged_gather's values, one page group at a time: a lax.fori_loop
    copies `unroll` pages per step into the assembly buffer — pure data
    movement, so the result is BIT-IDENTICAL to the bulk take; only the
    copy granularity (the knob a DMA pipeline tunes) differs."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import paged_attention as pa

    quant = isinstance(pool, pa.QuantPool)
    data = pool.data if quant else pool
    b, w = tables.shape
    _nb, nkv, bs, h = data.shape
    buf = jnp.zeros((b, w, nkv, bs, h),
                    jnp.float32 if quant else data.dtype)

    def step(i, buf):
        for t in range(unroll):
            wi = i * unroll + t
            for bi in range(b):
                idx = tables[bi, wi]
                blk = jax.lax.dynamic_index_in_dim(data, idx, 0,
                                                   keepdims=False)
                if quant:
                    sc = jax.lax.dynamic_index_in_dim(pool.scale, idx, 0,
                                                      keepdims=False)
                    blk = blk.astype(jnp.float32) * sc[:, None, None]
                buf = jax.lax.dynamic_update_slice(
                    buf, blk[None, None], (bi, wi, 0, 0, 0))
        return buf

    buf = jax.lax.fori_loop(0, w // unroll, step, buf)
    return jnp.moveaxis(buf, 2, 1).reshape(b, nkv, w * bs, h)


def _pool_specs(spec, whole):
    """(in_specs head, out_specs tail, out_shapes tail, n_leaves) for the
    k/v pool leaves — payload(+scales) per pool, whole-array blocks."""
    import jax
    import jax.numpy as jnp

    pool_shape = (spec.num_blocks, spec.num_kv_heads, spec.block_size,
                  spec.head_dim)
    pool_dt = jnp.int8 if spec.kv == "int8" else jnp.dtype(spec.dtype)
    if spec.kv == "int8":
        scale_shape = (spec.num_blocks, spec.num_kv_heads)
        per_pool = [(pool_shape, pool_dt), (scale_shape, jnp.float32)]
    else:
        per_pool = [(pool_shape, pool_dt)]
    leaves = per_pool + per_pool  # k then v
    in_specs = [whole(shape) for shape, _dt in leaves]
    out_specs = [whole(shape) for shape, _dt in leaves]
    out_shapes = [jax.ShapeDtypeStruct(shape, dt) for shape, dt in leaves]
    return in_specs, out_specs, out_shapes, len(per_pool)


def _build_batch(spec, config):
    """The whole-batch layout: ONE grid step replays the exact unfused op
    sequence (paged_write x2 → paged_gather/loop-gather →
    gathered_attention) over VMEM-resident pools — bit-exact vs the twin
    by construction, fused into a single HBM round trip."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.ops._pl_utils import imap

    int8 = spec.kv == "int8"
    gather = config.get("gather", "take")
    unroll = int(config.get("unroll", 1) or 1)
    b, n, nkv, h = (spec.batch, spec.num_heads, spec.num_kv_heads,
                    spec.head_dim)
    w = spec.max_blocks
    dt = jnp.dtype(spec.dtype)
    n_pool_in = 4 if int8 else 2

    def whole(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, imap(lambda i: (0,) * nd))

    def kernel(*refs):
        pool_ins = refs[:n_pool_in]
        q_r, kn_r, vn_r, tbl_r, ln_r = refs[n_pool_in:n_pool_in + 5]
        o_r = refs[n_pool_in + 5]
        pool_outs = refs[n_pool_in + 6:]
        tables = tbl_r[...]
        lens = ln_r[...]
        pos = lens - 1
        if int8:
            kpool = pa.QuantPool(pool_ins[0][...], pool_ins[1][...])
            vpool = pa.QuantPool(pool_ins[2][...], pool_ins[3][...])
        else:
            kpool, vpool = pool_ins[0][...], pool_ins[1][...]
        kpool = pa.paged_write(kpool, kn_r[...], tables, pos)
        vpool = pa.paged_write(vpool, vn_r[...], tables, pos)
        if int8:
            pool_outs[0][...] = kpool.data
            pool_outs[1][...] = kpool.scale
            pool_outs[2][...] = vpool.data
            pool_outs[3][...] = vpool.scale
        else:
            pool_outs[0][...] = kpool
            pool_outs[1][...] = vpool
        if gather == "take":
            keys = pa.paged_gather(kpool, tables)
            vals = pa.paged_gather(vpool, tables)
        else:
            keys = _loop_gather(kpool, tables, unroll)
            vals = _loop_gather(vpool, tables, unroll)
        o = pa.gathered_attention(q_r[...][:, None], keys, vals, lens)
        o_r[...] = o[:, 0].astype(o_r.dtype)

    pool_in_specs, pool_out_specs, pool_out_shapes, _ = _pool_specs(
        spec, whole)
    in_specs = pool_in_specs + [
        whole((b, n, h)), whole((b, nkv, h)), whole((b, nkv, h)),
        whole((b, w)), whole((b,))]
    out_specs = [whole((b, n, h))] + pool_out_specs
    out_shape = [jax.ShapeDtypeStruct((b, n, h), dt)] + pool_out_shapes
    aliases = {i: i + 1 for i in range(n_pool_in)}  # pools donate in place

    return _wrap_call(spec, kernel, (1,), in_specs, out_specs, out_shape,
                      aliases)


def _build_rows(spec, config):
    """The per-row layout (int8 only): grid over batch rows, each step
    writing its row's token into its OWN pool block (the engine's
    disjoint-ownership invariant) and gathering just that row's pages.
    Pools stay bit-exact (the running-max rescale replays
    _quant_write_chunk's math per row); the attention output re-associates
    the einsum and rides the int8 drift budget."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.ops._pl_utils import imap

    gather = config.get("gather", "take")
    unroll = int(config.get("unroll", 1) or 1)
    b, n, nkv, h = (spec.batch, spec.num_heads, spec.num_kv_heads,
                    spec.head_dim)
    bs, w = spec.block_size, spec.max_blocks
    dt = jnp.dtype(spec.dtype)
    qmax, eps = 127.0, 1e-12

    def whole(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, imap(lambda i: (0,) * nd))

    def row(shape):
        nd = len(shape)
        return pl.BlockSpec((1,) + shape[1:],
                            imap(lambda i: (i,) + (0,) * (nd - 1)))

    def kernel(*refs):
        kd, ks, vd, vs = refs[:4]
        q_r, kn_r, vn_r, tbl_r, ln_r, o_r = refs[4:10]
        okd, oks, ovd, ovs = refs[10:]
        ln = ln_r[0]
        pos = ln - 1
        bidx = tbl_r[0, pos // bs]
        slot = pos % bs

        def write(d_ref, s_ref, od_ref, os_ref, new):
            # _quant_write_chunk's math for ONE row's token: running-max
            # scale growth + in-place rescale of the touched block
            af = new.astype(jnp.float32)                    # [1, Nkv, H]
            tok = jnp.max(jnp.abs(af), axis=-1) / qmax      # [1, Nkv]
            old_s = pl.load(s_ref, (pl.ds(bidx, 1),))       # [1, Nkv]
            new_s = jnp.maximum(old_s, tok)
            safe = jnp.maximum(new_s, eps)
            old_b = pl.load(d_ref, (pl.ds(bidx, 1),)).astype(jnp.float32)
            ratio = jnp.where(new_s > old_s, old_s / safe, 1.0)
            resc = jnp.clip(jnp.round(old_b * ratio[..., None, None]),
                            -qmax, qmax).astype(jnp.int8)
            qv = jnp.clip(jnp.round(af / safe[..., None]),
                          -qmax, qmax).astype(jnp.int8)
            resc = jax.lax.dynamic_update_slice(
                resc, qv[:, :, None, :], (0, 0, slot, 0))
            pl.store(od_ref, (pl.ds(bidx, 1),), resc)
            pl.store(os_ref, (pl.ds(bidx, 1),), new_s)

        write(kd, ks, okd, oks, kn_r[...])
        write(vd, vs, ovd, ovs, vn_r[...])

        def gather_row(od_ref, os_ref):
            # this row's pages out of the WRITTEN pool; take and loop are
            # pure data movement over the same values (one definition of
            # the loop path: _loop_gather)
            pool = pa.QuantPool(od_ref[...], os_ref[...])
            if gather == "take":
                return pa.paged_gather(pool, tbl_r[...])
            return _loop_gather(pool, tbl_r[...], unroll)

        keys = gather_row(okd, oks)
        vals = gather_row(ovd, ovs)
        o = pa.gathered_attention(q_r[...][:, None], keys, vals, ln_r[...])
        o_r[...] = o[:, 0].astype(o_r.dtype)

    pool_in_specs, pool_out_specs, pool_out_shapes, _ = _pool_specs(
        spec, whole)
    in_specs = pool_in_specs + [
        row((b, n, h)), row((b, nkv, h)), row((b, nkv, h)),
        row((b, w)), row((b,))]
    out_specs = [row((b, n, h))] + pool_out_specs
    out_shape = [jax.ShapeDtypeStruct((b, n, h), dt)] + pool_out_shapes
    aliases = {i: i + 1 for i in range(4)}

    return _wrap_call(spec, kernel, (b,), in_specs, out_specs, out_shape,
                      aliases)


def _wrap_call(spec, kernel, grid, in_specs, out_specs, out_shape, aliases):
    """pallas_call wrapper taking the canonical (kc, vc, q, kn, vn,
    tables, lens) signature and returning (o, kc', vc') with QuantPools
    re-assembled leaf-wise."""
    import jax
    from jax.experimental import pallas as pl

    from paddle_tpu.ops import paged_attention as pa

    int8 = spec.kv == "int8"

    def fused(kc, vc, q, kn, vn, tables, lens):
        if int8:
            pool_leaves = (kc.data, kc.scale, vc.data, vc.scale)
        else:
            pool_leaves = (kc, vc)
        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            input_output_aliases=aliases,
            interpret=jax.default_backend() != "tpu",
        )(*pool_leaves, q, kn, vn, tables, lens)
        if int8:
            o, kd, ks, vd, vs = outs
            return o, pa.QuantPool(kd, ks), pa.QuantPool(vd, vs)
        o, kd, vd = outs
        return o, kd, vd

    return fused


def _build_sharded(spec, config):
    """The mesh chain: the SINGLE-DEVICE kernel at the device_spec's
    shard_shape geometry, wrapped in shard_map over the engine's
    committed layout.  GQA head contiguity makes every candidate layout
    head-local — device d's query-head shard attends exactly its own
    kv-head shard — so the body runs ZERO collectives and each device
    replays the bit-exact single-device math on its slice; the donation
    aliases ride through (pool shards update in place per device)."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.shard_map_compat import shard_map

    mp = spec._mp()
    if spec.num_heads % mp != 0 or spec.num_kv_heads % mp != 0:
        # a split kv group would need the epilogue psum collective_bytes
        # costs — no candidate implements it, and serving never gets here
        # (ineligible engines keep the counted mesh skip)
        raise ValueError(
            f"sharded decode chain needs head counts divisible by "
            f"{spec.mp_axis}={mp} (got n={spec.num_heads}, "
            f"nkv={spec.num_kv_heads}): a split GQA group requires an "
            "epilogue psum no layout implements")
    inner = spec.device_spec().build(config)
    pool_p, head_p = P(None, spec.mp_axis), P(None, spec.mp_axis, None)
    return shard_map(
        inner, mesh=spec.mesh.jax_mesh,
        in_specs=(pool_p, pool_p, head_p, head_p, head_p, P(), P()),
        out_specs=(head_p, pool_p, pool_p),
        check_vma=False)


# ---------------------------------------------------------------------------
# the prefill-attention chain: the OTHER serving hot path joins the search


@dataclass
class PrefillChainSpec:
    """One chunked-prefill attention call, ready to schedule: a query
    chunk of `seq` tokens against `kv_len` cached-plus-chunk positions
    (bottom-right aligned — chunk token i attends the cache and chunk
    positions <= i), heads POST-GQA-repeat, the exact geometry
    models/llama's LlamaAttention prefill branch hands
    F.scaled_dot_product_attention.

    Candidates keep the query grid at ONE tile (`block_q == seq`: the
    in-kernel attention call has EXACTLY the twin's shapes, so XLA
    compiles the same reduction order at every live kv length — a
    sub-tile's differently-shaped call may re-fuse and drift ~1e-7) and
    schedule the K/V staging granularity (`kchunk` pieces — pure data
    movement, the DMA knob), so the parity gate demands BIT-EXACT
    equality with the XLA twin, no tolerance tier."""

    seq: int
    kv_len: int
    num_heads: int
    head_dim: int
    dtype: object = np.float32

    check_parity = True

    # ------------------------------------------------------------ identity
    def kernel_name(self) -> str:
        return "schedule/prefill"

    def key(self) -> dict:
        return {
            "s": self.seq,
            "t": self.kv_len,
            "n": self.num_heads,
            "h": self.head_dim,
            "dtype": np.dtype(self.dtype).name,
        }

    def label(self) -> str:
        from paddle_tpu.ops.autotune import _key_str

        return f"{self.kernel_name()}|{_key_str(self.key())}"

    def config_label(self, config) -> str:
        lbl = f"#q{config.get('block_q', self.seq)}-{config.get('stage', 'take')}"
        if config.get("stage") == "loop":
            lbl += f"k{config.get('kchunk', 1)}"
        return lbl

    # ------------------------------------------------------ candidate space
    def enumerate_configs(self):
        """`block_q` — query tile height, pinned to the WHOLE chunk: a
        sub-tile's attention call has different shapes than the twin's,
        and XLA may re-fuse its reduction (~1e-7 drift, shape-dependent
        — a candidate could even pass parity at this spec's geometry yet
        drift at another live kv length, which the gate can't see).  One
        full-chunk tile keeps the in-kernel call shape-identical to the
        reference at EVERY kv length.  `stage` — 'take' hands the whole
        K/V block to the core, 'loop' assembles it from `kchunk` staged
        copies first (the K-tiled DMA granularity; values bit-identical
        either way).  seq >= 2 required: jax.nn.dot_product_attention
        special-cases single-row queries (decode shape) with a
        re-associated reduction."""
        if self.seq < 2:
            return []
        kchunks = [c for c in (2, 4)
                   if c <= self.kv_len and self.kv_len % c == 0]
        out = [{"block_q": self.seq, "stage": "take"}]
        for c in kchunks:
            out.append({"block_q": self.seq, "stage": "loop", "kchunk": c})
        return out

    # ------------------------------------------------------------ cost model
    def flops(self) -> float:
        s, t, n, h = self.seq, self.kv_len, self.num_heads, self.head_dim
        return 4.0 * n * s * t * h + 5.0 * n * s * t

    def traffic_bytes(self, config) -> int:
        """q/output once; K/V re-fetched once per query tile when the
        grid revisits them (the candidate_roofline_ms convention for a
        block whose index map is constant across the grid is fetch-once —
        but whole-block K/V here is re-staged per step off-chip unless
        the grid is a single step)."""
        it = np.dtype(self.dtype).itemsize
        s, t, n, h = self.seq, self.kv_len, self.num_heads, self.head_dim
        gq = s // int(config.get("block_q", s))
        traffic = 2 * s * n * h * it          # q in, output out
        traffic += 2 * t * n * h * it * gq    # k, v per query tile
        return int(traffic)

    def roofline_ms(self, config, cost_model=None) -> float:
        if cost_model is None:
            from paddle_tpu.cost_model import OpCostModel

            cost_model = OpCostModel()
        gq = self.seq // int(config.get("block_q", self.seq))
        copies = gq
        if config.get("stage") == "loop":
            copies += 2 * gq * int(config.get("kchunk", 1))
        return (cost_model.flops_time(self.flops(),
                                      self.traffic_bytes(config))
                + copies * _COPY_STEP_OVERHEAD_S) * 1e3

    def vmem_bytes(self, config) -> int:
        """Per grid step: the q tile, whole K/V (+ the staged copy for
        'loop'), the f32 logits tile, and the output tile — x2 for the
        double-buffer convention."""
        it = np.dtype(self.dtype).itemsize
        bq = int(config.get("block_q", self.seq))
        t, n, h = self.kv_len, self.num_heads, self.head_dim
        total = bq * n * h * it                  # q tile
        total += 2 * t * n * h * it              # k, v
        if config.get("stage") == "loop":
            total += 2 * t * n * h * it          # assembly buffers
        total += n * bq * t * 4                  # logits tile (f32)
        total += bq * n * h * it                 # output tile
        return int(total) * 2

    # ------------------------------------------------------------- numerics
    def reference(self):
        """The XLA twin: EXACTLY the nn.functional.attention._core math
        the model otherwise runs — jax.nn.dot_product_attention, causal
        top-left for the square first chunk, the explicit bottom-right
        tri mask for a chunk on a longer cache."""
        import jax
        import jax.numpy as jnp

        def ref(q, k, v):
            sq, sk = q.shape[1], k.shape[1]
            if sq != sk:
                tri = jnp.tril(jnp.ones((sq, sk), bool),
                               k=sk - sq)[None, None]
                return jax.nn.dot_product_attention(q, k, v, mask=tri,
                                                    is_causal=False)
            return jax.nn.dot_product_attention(q, k, v, is_causal=True)

        return ref

    def synthetic_args(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        dt = jnp.dtype(self.dtype)
        s, t, n, h = self.seq, self.kv_len, self.num_heads, self.head_dim
        return (jnp.asarray(rng.standard_normal((1, s, n, h)), dt),
                jnp.asarray(rng.standard_normal((1, t, n, h)), dt),
                jnp.asarray(rng.standard_normal((1, t, n, h)), dt))

    def parity_ok(self, fn, args, reference_out) -> bool:
        """Bit-exact, no tolerance tier: the full-chunk tile keeps the
        in-kernel attention call shape-identical to the twin (same XLA
        reduction order) and staging is pure data movement."""
        try:
            got = fn(*args)
        except Exception:
            return False
        return (got.shape == reference_out.shape
                and got.dtype == reference_out.dtype
                and bool((got == reference_out).all()))

    # --------------------------------------------------------------- build
    def build(self, config):
        return _build_prefill(self, config)


def _stage_chunks(src, kchunk):
    """K/V assembly in `kchunk` pieces: a fori_loop copies each chunk of
    the kv axis into the buffer — pure data movement (bit-identical to
    using `src` directly), only the copy granularity differs."""
    import jax
    import jax.numpy as jnp

    t = src.shape[1]
    step_len = t // kchunk
    buf = jnp.zeros_like(src)

    def step(j, buf):
        sl = jax.lax.dynamic_slice_in_dim(src, j * step_len, step_len,
                                          axis=1)
        return jax.lax.dynamic_update_slice_in_dim(buf, sl, j * step_len,
                                                   axis=1)

    return jax.lax.fori_loop(0, kchunk, step, buf)


def _build_prefill(spec, config):
    """Grid over query-row tiles, whole K/V resident per step: each step
    replays the EXACT reference call (jax.nn.dot_product_attention with
    this tile's bottom-right mask rows) on its rows — bit-exact vs the
    twin by construction, the decode-chain philosophy at prefill
    shapes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.ops._pl_utils import imap

    s, t, n, h = spec.seq, spec.kv_len, spec.num_heads, spec.head_dim
    bq = int(config.get("block_q", s))
    stage = config.get("stage", "take")
    kchunk = int(config.get("kchunk", 1) or 1)
    dt = jnp.dtype(spec.dtype)
    gq = s // bq

    def kernel(q_r, k_r, v_r, o_r):
        i = pl.program_id(0)
        k = k_r[...]
        v = v_r[...]
        if stage == "loop":
            k = _stage_chunks(k, kchunk)
            v = _stage_chunks(v, kchunk)
        rows = i * bq + jnp.arange(bq)
        # this tile's rows of tril(ones((s, t)), k=t-s): bottom-right
        # aligned — identical to the causal path for the square chunk
        mask = (jnp.arange(t)[None, :]
                <= rows[:, None] + (t - s))[None, None]
        o = jax.nn.dot_product_attention(q_r[...], k, v, mask=mask,
                                         is_causal=False)
        o_r[...] = o.astype(o_r.dtype)

    def qtile(shape):
        return pl.BlockSpec((1, bq) + shape[2:],
                            imap(lambda i: (0, i, 0, 0)))

    def whole(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, imap(lambda i: (0,) * nd))

    def fused(q, k, v):
        return pl.pallas_call(
            kernel,
            grid=(gq,),
            in_specs=[qtile((1, s, n, h)), whole((1, t, n, h)),
                      whole((1, t, n, h))],
            out_specs=qtile((1, s, n, h)),
            out_shape=jax.ShapeDtypeStruct((1, s, n, h), dt),
            interpret=jax.default_backend() != "tpu",
        )(q, k, v)

    return fused


# ---------------------------------------------------------------------------
# engine-facing plumbing


def spec_from_arrays(kc, q, tables, mesh=None, mp_axis="mp"):
    """Geometry spec for the chain the traced step is about to run —
    derived from the live pool/query/table shapes, so the fused kernel
    and the arrays it consumes can never disagree."""
    from paddle_tpu.ops import paged_attention as pa

    quant = isinstance(kc, pa.QuantPool)
    data = kc.data if quant else kc
    nb, nkv, bs, h = data.shape
    b, n, _h = q.shape
    return DecodeChainSpec(
        batch=int(b), num_heads=int(n), num_kv_heads=int(nkv),
        head_dim=int(h), block_size=int(bs),
        max_blocks=int(tables.shape[1]), num_blocks=int(nb),
        kv="int8" if quant else "bf16",
        dtype=np.dtype(q.dtype), mesh=mesh, mp_axis=mp_axis)


def ensure_decision(spec, searcher=None):
    """Search-or-serve for one decode-chain geometry: cache verdicts are
    final (accepted configs serve with ZERO re-measurement; disabled
    geometries never re-fire), fresh geometries run the full
    enumerate→prune→parity→measure→gate loop and persist.  A
    cache-served config is parity-gated once per consumer anyway — a
    cache file is trusted about SPEED, never about numerics."""
    import jax

    from paddle_tpu.static.schedule_search import Decision, ScheduleSearcher

    if searcher is None:
        searcher = ScheduleSearcher()
    decision = searcher.search(spec)
    if decision.status == "cache":
        try:
            args = spec.synthetic_args()
            ref_out = jax.jit(spec.reference())(*args)
            if not spec.parity_ok(jax.jit(spec.build(decision.config)),
                                  args, ref_out):
                return Decision("disabled")
        except Exception:
            return Decision("disabled")
    return decision


def fused_decode_step(kc, vc, q, kn, vn, tables, lens, *, config):
    """The macro-step scan body's fused seam: one accepted-config Pallas
    dispatch replacing the write→write→attend op sequence of
    models/llama._decode_layer_paged.  Returns (o, kc', vc').

    A TP-sharded engine injects its mesh handle as the non-persisted
    '_mesh'/'_mp_axis' config entries (serving._resolve_decode_chain) —
    popped here before build, so the cache stores the pure schedule and
    the live mesh object never leaks into a verdict file."""
    config = dict(config)
    mesh = config.pop("_mesh", None)
    mp_axis = config.pop("_mp_axis", "mp")
    spec = spec_from_arrays(kc, q, tables, mesh=mesh, mp_axis=mp_axis)
    return spec.build(config)(kc, vc, q, kn, vn, tables, lens)


def fused_prefill_attention(q, k, v, *, block_q, stage="take", kchunk=1):
    """The prefill branch's fused seam (LlamaAttention.forward under
    models/llama.prefill_chain_scope): one accepted-config Pallas
    dispatch replacing the F.scaled_dot_product_attention core for a
    [1, S, n, h] chunk against [1, T, n, h] post-repeat K/V.  Callers
    gate on divisibility (S % block_q, T % kchunk) — a chunk the config
    doesn't tile keeps the XLA path."""
    _b, s, n, h = q.shape
    spec = PrefillChainSpec(seq=int(s), kv_len=int(k.shape[1]),
                            num_heads=int(n), head_dim=int(h),
                            dtype=np.dtype(q.dtype))
    cfg = {"block_q": int(block_q), "stage": stage, "kchunk": int(kchunk)}
    return spec.build(cfg)(q, k, v)
