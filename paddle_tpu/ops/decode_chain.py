"""Searchable fused decode hot chain: paged gather → dequant → sdpa core →
(running-max) quant-write as ONE Pallas dispatch per layer per token.

Schedule search, phase 2 (ROADMAP item 4; docs/SCHEDULE_SEARCH.md).  The
decode macro-step's per-token chain runs today as separate XLA ops inside
the jitted scan body — exactly the memory-bound fusion-miss class
"Operator Fusion in XLA" (arXiv 2301.13062) catalogs.  This module makes
that chain a SEARCHABLE subgraph for static/schedule_search.py's
ScheduleSearcher: `DecodeChainSpec` describes the chain at one engine
geometry and implements the same searcher protocol Program subgraphs use
(enumerate → roofline → VMEM → parity → measure → measured-win gate), so
winners and losers persist per device kind under the `schedule/decode_*`
AutotuneCache namespaces and the engine's compiled macro-step consumes an
accepted config with zero re-measurement (serving._resolve_decode_chain).

Semantics are NEVER trusted to the gate: every candidate must pass a
numerics parity check against the XLA twin BEFORE it may be measured
(`check_parity`), with the same contract the engine's stream tests
enforce — full-precision ('bf16') pools bit-exact, int8 pools bit-exact
on the quantized payload/scales with the attention output inside the
PR-6 drift budget.  That is why the default `batch` layout replays the
EXACT unfused ops (paged_write / paged_gather / gathered_attention — one
definition each, imported from ops.paged_attention) inside one
pallas_call: fusion changes the number of HBM round trips, never the
math.  The int8-only `rows` layout grids over batch rows (smaller VMEM
working set, whole-pool re-staging per row in the traffic model) and is
tolerance-gated on the attention output.

Mixed-dtype roofline honesty: a QuantPool chain moves int8 payload bytes
AND float32 scale bytes — `traffic_bytes` costs every pool leaf at its
OWN itemsize instead of assuming one dtype for the whole subgraph (the
bf16-pool chain at identical geometry models ~2x the gather traffic,
which is the int8 capacity story told by the cost model).

CPU/on-chip honesty: kernels run in Pallas interpret mode off-TPU, where
XLA usually wins and the gate (correctly) disables — tests and the bench
--smoke twin decide through schedule_search.measure_override.  On TPU the
whole-pool VMEM residency of these layouts is validated by
ops.autotune.validate_tile, so geometries whose pools exceed the budget
are pruned honestly rather than faked; a DMA-pipelined variant can join
the candidate space later without changing the search contract.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

__all__ = [
    "DecodeChainSpec",
    "spec_from_arrays",
    "ensure_decision",
    "fused_decode_step",
]

# per-copy-step turnaround for the analytic ranking (the scale of one DMA
# issue): breaks ties between gather granularities whose traffic is
# identical, the same role schedule_search._GRID_STEP_OVERHEAD_S plays
# for 1-D grids
_COPY_STEP_OVERHEAD_S = 1e-7


@dataclass
class DecodeChainSpec:
    """One engine geometry's decode hot chain, ready to schedule.

    kv: 'bf16' (full-precision pools in `dtype`) | 'int8' (QuantPool —
    int8 payload + per-block-per-head f32 scales, running-max writes).
    num_blocks counts the WHOLE pool incl. scratch pages; max_blocks is
    the per-sequence block-table width."""

    batch: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    block_size: int
    max_blocks: int
    num_blocks: int
    kv: str = "bf16"
    dtype: object = np.float32

    check_parity = True  # searcher protocol: candidates numerics-gate

    def __post_init__(self):
        if self.kv not in ("bf16", "int8"):
            raise ValueError(f"kv must be 'bf16' or 'int8', got {self.kv!r}")

    # ------------------------------------------------------------ identity
    @property
    def seq(self) -> int:
        return self.max_blocks * self.block_size

    def kernel_name(self) -> str:
        return f"schedule/decode_{self.kv}"

    def key(self) -> dict:
        return {
            "b": self.batch,
            "n": self.num_heads,
            "nkv": self.num_kv_heads,
            "h": self.head_dim,
            "bs": self.block_size,
            "w": self.max_blocks,
            "nb": self.num_blocks,
            "dtype": np.dtype(self.dtype).name,
        }

    def label(self) -> str:
        from paddle_tpu.ops.autotune import _key_str

        return f"{self.kernel_name()}|{_key_str(self.key())}"

    def config_label(self, config) -> str:
        lbl = f"#{config.get('layout', 'batch')}-{config.get('gather', 'take')}"
        if config.get("gather") == "loop":
            lbl += f"u{config.get('unroll', 1)}"
        return lbl

    # ------------------------------------------------------ candidate space
    def enumerate_configs(self):
        """Schedule space: `layout` — 'batch' replays the whole batch in
        one grid step (bit-exact by construction; the only layout a
        'bf16' chain may use), 'rows' (int8 only) grids over batch rows;
        `gather` — 'take' stages pages in one bulk gather, 'loop' copies
        `unroll` pages per step (the DMA granularity knob; values are
        bit-identical either way — gathering is pure data movement)."""
        unrolls = [u for u in (1, 2, 4)
                   if u <= self.max_blocks and self.max_blocks % u == 0]
        layouts = ["batch"] + (["rows"] if self.kv == "int8" else [])
        out = []
        for layout in layouts:
            out.append({"layout": layout, "gather": "take"})
            for u in unrolls:
                out.append({"layout": layout, "gather": "loop", "unroll": u})
        return out

    # ------------------------------------------------------------ cost model
    def _leaf_bytes(self):
        """[(name, nbytes)] per pool LEAF at its OWN dtype — one pool's
        int8 payload and f32 scales are costed separately (the mixed-dtype
        fix: a QuantPool chain is not 'one dtype' to the roofline)."""
        nb, nkv, bs, h = (self.num_blocks, self.num_kv_heads,
                          self.block_size, self.head_dim)
        if self.kv == "int8":
            return [("payload", nb * nkv * bs * h * 1),
                    ("scale", nb * nkv * 4)]
        return [("payload", nb * nkv * bs * h
                 * np.dtype(self.dtype).itemsize)]

    def _write_bytes(self):
        """HBM bytes the chain's write phase touches, per pool: bf16
        writes one token slot per row; int8 rewrites each touched block
        (running-max rescale) plus its f32 scales."""
        b, nkv, bs, h = (self.batch, self.num_kv_heads, self.block_size,
                         self.head_dim)
        if self.kv == "int8":
            return b * nkv * bs * h * 1 + b * nkv * 4
        return b * nkv * h * np.dtype(self.dtype).itemsize

    def traffic_bytes(self, config) -> int:
        """Modeled HBM traffic: every pool leaf read at its own itemsize
        (once for the 'batch' layout; re-staged per row — x batch — for
        'rows'), the write phase's touched bytes, and the q/k/v/token
        tensors + output once."""
        it = np.dtype(self.dtype).itemsize
        b, n, nkv, h = (self.batch, self.num_heads, self.num_kv_heads,
                        self.head_dim)
        read_factor = b if config.get("layout") == "rows" else 1
        pool_reads = 2 * sum(sz for _name, sz in self._leaf_bytes())
        traffic = pool_reads * read_factor
        traffic += 2 * self._write_bytes()
        traffic += b * n * h * it            # q
        traffic += 2 * b * nkv * h * it      # k_new, v_new
        traffic += b * self.max_blocks * 4 + b * 4  # tables, lens
        traffic += b * n * h * it            # attention output
        return int(traffic)

    def flops(self) -> float:
        b, n, h, s = self.batch, self.num_heads, self.head_dim, self.seq
        return 4.0 * b * n * s * h + 5.0 * b * n * s

    def roofline_ms(self, config, cost_model=None) -> float:
        if cost_model is None:
            from paddle_tpu.cost_model import OpCostModel

            cost_model = OpCostModel()
        if config.get("gather") == "loop":
            u = int(config.get("unroll", 1) or 1)
            # one copy per page group per row per pool
            copies = 2 * self.batch * (self.max_blocks // u)
        else:
            copies = 2  # one bulk gather per pool
        return (cost_model.flops_time(self.flops(),
                                      self.traffic_bytes(config))
                + copies * _COPY_STEP_OVERHEAD_S) * 1e3

    def vmem_bytes(self, config) -> int:
        """f32-staged working set per grid step (double-buffered, the
        validate_tile convention): the resident pool leaves plus the
        per-step gathered views, logits tile, and token blocks.  The
        'rows' layout holds one row's views; both layouts keep the whole
        pool resident — on-chip geometries whose pools exceed VMEM are
        pruned honestly here."""
        it = np.dtype(self.dtype).itemsize
        rows = 1 if config.get("layout") == "rows" else self.batch
        n, nkv, h, s = (self.num_heads, self.num_kv_heads, self.head_dim,
                        self.seq)
        total = 2 * sum(sz for _name, sz in self._leaf_bytes())  # pools
        total += 2 * rows * nkv * s * h * 4        # gathered k/v (f32)
        total += rows * n * s * 4                  # logits tile
        total += rows * (n + 2 * nkv) * h * it     # q, k_new, v_new
        total += rows * n * h * it                 # output block
        return int(total) * 2

    # ------------------------------------------------------------- numerics
    def reference(self):
        """The XLA twin: EXACTLY the unfused macro-step sequence
        (models/llama._decode_layer_paged lines write→write→attend)."""
        from paddle_tpu.ops import paged_attention as pa

        def ref(kc, vc, q, kn, vn, tables, lens):
            pos = lens - 1
            kc = pa.paged_write(kc, kn, tables, pos)
            vc = pa.paged_write(vc, vn, tables, pos)
            o = pa.paged_decode_attention(q, kc, vc, tables, lens)
            return o, kc, vc

        return ref

    def synthetic_args(self):
        """Deterministic engine-shaped args: every row owns DISJOINT
        pool blocks (the engine's allocator invariant the 'rows' layout
        relies on) poured with random content, lengths spread over the
        table span."""
        import jax.numpy as jnp

        from paddle_tpu.ops import paged_attention as pa

        b, n, nkv, h = (self.batch, self.num_heads, self.num_kv_heads,
                        self.head_dim)
        bs, w = self.block_size, self.max_blocks
        rng = np.random.default_rng(0)
        dt = jnp.dtype(self.dtype)
        kc, vc = pa.alloc_paged_cache(
            self.num_blocks, nkv, bs, h,
            jnp.int8 if self.kv == "int8" else dt)
        ids = np.arange(b * w, dtype=np.int32).reshape(b, w)
        kv = jnp.asarray(rng.standard_normal((b * w, nkv, bs, h)),
                         jnp.float32)
        vv = jnp.asarray(rng.standard_normal((b * w, nkv, bs, h)),
                         jnp.float32)
        kc = pa.paged_pour_blocks(kc, kv, ids.reshape(-1))
        vc = pa.paged_pour_blocks(vc, vv, ids.reshape(-1))
        s = self.seq
        lens = np.clip(np.linspace(2, s, b).astype(np.int32), 2, s)
        return (kc, vc,
                jnp.asarray(rng.standard_normal((b, n, h)), dt),
                jnp.asarray(rng.standard_normal((b, nkv, h)), dt),
                jnp.asarray(rng.standard_normal((b, nkv, h)), dt),
                jnp.asarray(ids), jnp.asarray(lens))

    def parity_ok(self, fn, args, reference_out) -> bool:
        """The parity gate: pools must match the twin BIT-EXACTLY for
        both kv kinds (quantized writes are deterministic integer math);
        the attention output must be bit-exact for 'bf16' and inside the
        documented PR-6 drift budget for 'int8' (the 'rows' layout
        re-associates the per-row einsum)."""
        import jax

        try:
            got = fn(*args)
        except Exception:
            return False
        r_leaves = jax.tree_util.tree_leaves(reference_out)
        g_leaves = jax.tree_util.tree_leaves(got)
        if len(r_leaves) != len(g_leaves):
            return False
        for i, (r, g) in enumerate(zip(r_leaves, g_leaves)):
            if r.shape != g.shape or r.dtype != g.dtype:
                return False
            if i == 0 and self.kv == "int8":  # attention output leaf
                if not np.allclose(np.asarray(r, np.float32),
                                   np.asarray(g, np.float32),
                                   rtol=1e-3, atol=1e-4):
                    return False
            elif not bool((r == g).all()):
                return False
        return True

    # --------------------------------------------------------------- build
    def build(self, config):
        if config.get("layout") == "rows":
            if self.kv != "int8":
                raise ValueError(
                    "the per-row layout re-associates the attention "
                    "einsum: bf16 chains are bit-exact-only ('batch')")
            return _build_rows(self, config)
        return _build_batch(self, config)


def _loop_gather(pool, tables, unroll):
    """paged_gather's values, one page group at a time: a lax.fori_loop
    copies `unroll` pages per step into the assembly buffer — pure data
    movement, so the result is BIT-IDENTICAL to the bulk take; only the
    copy granularity (the knob a DMA pipeline tunes) differs."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import paged_attention as pa

    quant = isinstance(pool, pa.QuantPool)
    data = pool.data if quant else pool
    b, w = tables.shape
    _nb, nkv, bs, h = data.shape
    buf = jnp.zeros((b, w, nkv, bs, h),
                    jnp.float32 if quant else data.dtype)

    def step(i, buf):
        for t in range(unroll):
            wi = i * unroll + t
            for bi in range(b):
                idx = tables[bi, wi]
                blk = jax.lax.dynamic_index_in_dim(data, idx, 0,
                                                   keepdims=False)
                if quant:
                    sc = jax.lax.dynamic_index_in_dim(pool.scale, idx, 0,
                                                      keepdims=False)
                    blk = blk.astype(jnp.float32) * sc[:, None, None]
                buf = jax.lax.dynamic_update_slice(
                    buf, blk[None, None], (bi, wi, 0, 0, 0))
        return buf

    buf = jax.lax.fori_loop(0, w // unroll, step, buf)
    return jnp.moveaxis(buf, 2, 1).reshape(b, nkv, w * bs, h)


def _pool_specs(spec, whole):
    """(in_specs head, out_specs tail, out_shapes tail, n_leaves) for the
    k/v pool leaves — payload(+scales) per pool, whole-array blocks."""
    import jax
    import jax.numpy as jnp

    pool_shape = (spec.num_blocks, spec.num_kv_heads, spec.block_size,
                  spec.head_dim)
    pool_dt = jnp.int8 if spec.kv == "int8" else jnp.dtype(spec.dtype)
    if spec.kv == "int8":
        scale_shape = (spec.num_blocks, spec.num_kv_heads)
        per_pool = [(pool_shape, pool_dt), (scale_shape, jnp.float32)]
    else:
        per_pool = [(pool_shape, pool_dt)]
    leaves = per_pool + per_pool  # k then v
    in_specs = [whole(shape) for shape, _dt in leaves]
    out_specs = [whole(shape) for shape, _dt in leaves]
    out_shapes = [jax.ShapeDtypeStruct(shape, dt) for shape, dt in leaves]
    return in_specs, out_specs, out_shapes, len(per_pool)


def _build_batch(spec, config):
    """The whole-batch layout: ONE grid step replays the exact unfused op
    sequence (paged_write x2 → paged_gather/loop-gather →
    gathered_attention) over VMEM-resident pools — bit-exact vs the twin
    by construction, fused into a single HBM round trip."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.ops._pl_utils import imap

    int8 = spec.kv == "int8"
    gather = config.get("gather", "take")
    unroll = int(config.get("unroll", 1) or 1)
    b, n, nkv, h = (spec.batch, spec.num_heads, spec.num_kv_heads,
                    spec.head_dim)
    w = spec.max_blocks
    dt = jnp.dtype(spec.dtype)
    n_pool_in = 4 if int8 else 2

    def whole(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, imap(lambda i: (0,) * nd))

    def kernel(*refs):
        pool_ins = refs[:n_pool_in]
        q_r, kn_r, vn_r, tbl_r, ln_r = refs[n_pool_in:n_pool_in + 5]
        o_r = refs[n_pool_in + 5]
        pool_outs = refs[n_pool_in + 6:]
        tables = tbl_r[...]
        lens = ln_r[...]
        pos = lens - 1
        if int8:
            kpool = pa.QuantPool(pool_ins[0][...], pool_ins[1][...])
            vpool = pa.QuantPool(pool_ins[2][...], pool_ins[3][...])
        else:
            kpool, vpool = pool_ins[0][...], pool_ins[1][...]
        kpool = pa.paged_write(kpool, kn_r[...], tables, pos)
        vpool = pa.paged_write(vpool, vn_r[...], tables, pos)
        if int8:
            pool_outs[0][...] = kpool.data
            pool_outs[1][...] = kpool.scale
            pool_outs[2][...] = vpool.data
            pool_outs[3][...] = vpool.scale
        else:
            pool_outs[0][...] = kpool
            pool_outs[1][...] = vpool
        if gather == "take":
            keys = pa.paged_gather(kpool, tables)
            vals = pa.paged_gather(vpool, tables)
        else:
            keys = _loop_gather(kpool, tables, unroll)
            vals = _loop_gather(vpool, tables, unroll)
        o = pa.gathered_attention(q_r[...][:, None], keys, vals, lens)
        o_r[...] = o[:, 0].astype(o_r.dtype)

    pool_in_specs, pool_out_specs, pool_out_shapes, _ = _pool_specs(
        spec, whole)
    in_specs = pool_in_specs + [
        whole((b, n, h)), whole((b, nkv, h)), whole((b, nkv, h)),
        whole((b, w)), whole((b,))]
    out_specs = [whole((b, n, h))] + pool_out_specs
    out_shape = [jax.ShapeDtypeStruct((b, n, h), dt)] + pool_out_shapes
    aliases = {i: i + 1 for i in range(n_pool_in)}  # pools donate in place

    return _wrap_call(spec, kernel, (1,), in_specs, out_specs, out_shape,
                      aliases)


def _build_rows(spec, config):
    """The per-row layout (int8 only): grid over batch rows, each step
    writing its row's token into its OWN pool block (the engine's
    disjoint-ownership invariant) and gathering just that row's pages.
    Pools stay bit-exact (the running-max rescale replays
    _quant_write_chunk's math per row); the attention output re-associates
    the einsum and rides the int8 drift budget."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.ops._pl_utils import imap

    gather = config.get("gather", "take")
    unroll = int(config.get("unroll", 1) or 1)
    b, n, nkv, h = (spec.batch, spec.num_heads, spec.num_kv_heads,
                    spec.head_dim)
    bs, w = spec.block_size, spec.max_blocks
    dt = jnp.dtype(spec.dtype)
    qmax, eps = 127.0, 1e-12

    def whole(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, imap(lambda i: (0,) * nd))

    def row(shape):
        nd = len(shape)
        return pl.BlockSpec((1,) + shape[1:],
                            imap(lambda i: (i,) + (0,) * (nd - 1)))

    def kernel(*refs):
        kd, ks, vd, vs = refs[:4]
        q_r, kn_r, vn_r, tbl_r, ln_r, o_r = refs[4:10]
        okd, oks, ovd, ovs = refs[10:]
        ln = ln_r[0]
        pos = ln - 1
        bidx = tbl_r[0, pos // bs]
        slot = pos % bs

        def write(d_ref, s_ref, od_ref, os_ref, new):
            # _quant_write_chunk's math for ONE row's token: running-max
            # scale growth + in-place rescale of the touched block
            af = new.astype(jnp.float32)                    # [1, Nkv, H]
            tok = jnp.max(jnp.abs(af), axis=-1) / qmax      # [1, Nkv]
            old_s = pl.load(s_ref, (pl.ds(bidx, 1),))       # [1, Nkv]
            new_s = jnp.maximum(old_s, tok)
            safe = jnp.maximum(new_s, eps)
            old_b = pl.load(d_ref, (pl.ds(bidx, 1),)).astype(jnp.float32)
            ratio = jnp.where(new_s > old_s, old_s / safe, 1.0)
            resc = jnp.clip(jnp.round(old_b * ratio[..., None, None]),
                            -qmax, qmax).astype(jnp.int8)
            qv = jnp.clip(jnp.round(af / safe[..., None]),
                          -qmax, qmax).astype(jnp.int8)
            resc = jax.lax.dynamic_update_slice(
                resc, qv[:, :, None, :], (0, 0, slot, 0))
            pl.store(od_ref, (pl.ds(bidx, 1),), resc)
            pl.store(os_ref, (pl.ds(bidx, 1),), new_s)

        write(kd, ks, okd, oks, kn_r[...])
        write(vd, vs, ovd, ovs, vn_r[...])

        def gather_row(od_ref, os_ref):
            # this row's pages out of the WRITTEN pool; take and loop are
            # pure data movement over the same values (one definition of
            # the loop path: _loop_gather)
            pool = pa.QuantPool(od_ref[...], os_ref[...])
            if gather == "take":
                return pa.paged_gather(pool, tbl_r[...])
            return _loop_gather(pool, tbl_r[...], unroll)

        keys = gather_row(okd, oks)
        vals = gather_row(ovd, ovs)
        o = pa.gathered_attention(q_r[...][:, None], keys, vals, ln_r[...])
        o_r[...] = o[:, 0].astype(o_r.dtype)

    pool_in_specs, pool_out_specs, pool_out_shapes, _ = _pool_specs(
        spec, whole)
    in_specs = pool_in_specs + [
        row((b, n, h)), row((b, nkv, h)), row((b, nkv, h)),
        row((b, w)), row((b,))]
    out_specs = [row((b, n, h))] + pool_out_specs
    out_shape = [jax.ShapeDtypeStruct((b, n, h), dt)] + pool_out_shapes
    aliases = {i: i + 1 for i in range(4)}

    return _wrap_call(spec, kernel, (b,), in_specs, out_specs, out_shape,
                      aliases)


def _wrap_call(spec, kernel, grid, in_specs, out_specs, out_shape, aliases):
    """pallas_call wrapper taking the canonical (kc, vc, q, kn, vn,
    tables, lens) signature and returning (o, kc', vc') with QuantPools
    re-assembled leaf-wise."""
    import jax
    from jax.experimental import pallas as pl

    from paddle_tpu.ops import paged_attention as pa

    int8 = spec.kv == "int8"

    def fused(kc, vc, q, kn, vn, tables, lens):
        if int8:
            pool_leaves = (kc.data, kc.scale, vc.data, vc.scale)
        else:
            pool_leaves = (kc, vc)
        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            input_output_aliases=aliases,
            interpret=jax.default_backend() != "tpu",
        )(*pool_leaves, q, kn, vn, tables, lens)
        if int8:
            o, kd, ks, vd, vs = outs
            return o, pa.QuantPool(kd, ks), pa.QuantPool(vd, vs)
        o, kd, vd = outs
        return o, kd, vd

    return fused


# ---------------------------------------------------------------------------
# engine-facing plumbing


def spec_from_arrays(kc, q, tables):
    """Geometry spec for the chain the traced step is about to run —
    derived from the live pool/query/table shapes, so the fused kernel
    and the arrays it consumes can never disagree."""
    from paddle_tpu.ops import paged_attention as pa

    quant = isinstance(kc, pa.QuantPool)
    data = kc.data if quant else kc
    nb, nkv, bs, h = data.shape
    b, n, _h = q.shape
    return DecodeChainSpec(
        batch=int(b), num_heads=int(n), num_kv_heads=int(nkv),
        head_dim=int(h), block_size=int(bs),
        max_blocks=int(tables.shape[1]), num_blocks=int(nb),
        kv="int8" if quant else "bf16",
        dtype=np.dtype(q.dtype))


def ensure_decision(spec, searcher=None):
    """Search-or-serve for one decode-chain geometry: cache verdicts are
    final (accepted configs serve with ZERO re-measurement; disabled
    geometries never re-fire), fresh geometries run the full
    enumerate→prune→parity→measure→gate loop and persist.  A
    cache-served config is parity-gated once per consumer anyway — a
    cache file is trusted about SPEED, never about numerics."""
    import jax

    from paddle_tpu.static.schedule_search import Decision, ScheduleSearcher

    if searcher is None:
        searcher = ScheduleSearcher()
    decision = searcher.search(spec)
    if decision.status == "cache":
        try:
            args = spec.synthetic_args()
            ref_out = jax.jit(spec.reference())(*args)
            if not spec.parity_ok(jax.jit(spec.build(decision.config)),
                                  args, ref_out):
                return Decision("disabled")
        except Exception:
            return Decision("disabled")
    return decision


def fused_decode_step(kc, vc, q, kn, vn, tables, lens, *, config):
    """The macro-step scan body's fused seam: one accepted-config Pallas
    dispatch replacing the write→write→attend op sequence of
    models/llama._decode_layer_paged.  Returns (o, kc', vc')."""
    spec = spec_from_arrays(kc, q, tables)
    return spec.build(config)(kc, vc, q, kn, vn, tables, lens)
