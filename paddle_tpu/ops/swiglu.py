"""Fused SwiGLU (silu(x) * gate) Pallas kernel.

Reference: paddle.incubate.nn.functional.swiglu (fused in
paddle/phi/kernels/fusion/gpu; used by LLaMA MLP).  Elementwise VPU kernel
with fp32 math and analytic backward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops._pl_utils import imap


def _swiglu_kernel(x_ref, y_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    o_ref[:] = (x * jax.nn.sigmoid(x) * y).astype(o_ref.dtype)


def _swiglu_apply(x2d, y2d, rows_block=None, cols_block=None):
    rows, cols = x2d.shape
    br, bc = rows_block, cols_block
    if br is None or bc is None:
        # autotune cache first (per device kind; ops/autotune.py)
        from paddle_tpu.ops import autotune as _at

        tuned = _at.lookup("swiglu", {"rows": rows, "cols": cols,
                                      "dtype": x2d.dtype.name})
        if tuned:
            tr, tc = int(tuned["rows_block"]), int(tuned["cols_block"])
            if rows % tr == 0 and cols % tc == 0:
                br, bc = br or tr, bc or tc
    if br is None:
        br = min(256, rows)
    if rows % br:
        br = rows
    # Tile the lane dim too: a (br, cols) block at large intermediate sizes
    # (e.g. 8192x5632) needs >16MB of double-buffered VMEM and fails to
    # allocate.  Elementwise kernel, so any 128-multiple tile is valid;
    # fall back to the full width only when cols has no such divisor.
    if bc is None or cols % bc:
        bc = cols
        for cand in (2048, 1024, 512, 256, 128):
            if cols % cand == 0:
                bc = cand
                break
    return pl.pallas_call(
        _swiglu_kernel,
        grid=(rows // br, cols // bc),
        in_specs=[
            pl.BlockSpec((br, bc), imap(lambda i, j: (i, j))),
            pl.BlockSpec((br, bc), imap(lambda i, j: (i, j))),
        ],
        out_specs=pl.BlockSpec((br, bc), imap(lambda i, j: (i, j))),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x2d.dtype),
        interpret=jax.default_backend() != "tpu",
    )(x2d, y2d)


@jax.custom_vjp
def _swiglu(x, y):
    shape = x.shape
    return _swiglu_apply(x.reshape(-1, shape[-1]), y.reshape(-1, shape[-1])).reshape(shape)


def _swiglu_fwd(x, y):
    return _swiglu(x, y), (x, y)


def _swiglu_bwd(res, g):
    x, y = res
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    sig = jax.nn.sigmoid(xf)
    silu = xf * sig
    dsilu = sig * (1.0 + xf * (1.0 - sig))
    return (gf * yf * dsilu).astype(x.dtype), (gf * silu).astype(y.dtype)


_swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


def swiglu(x, y=None):
    """swiglu(x, y) = silu(x) * y; if y is None, x is split in half on the
    last axis (reference semantics)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return _swiglu(x, y)
