"""Kernel autotune: per-shape tile search with a persistent per-device cache.

Reference: the CINN auto-scheduler (paddle/cinn/auto_schedule/auto_tuner.h —
search over schedule configs driven by measured cost) and the phi kernel
autotune cache (paddle/phi/kernels/autotune/cache.h — per-(op, key) config
cache consulted by kernel launch).

TPU-native redesign: XLA already schedules fused HLO, so the tunable surface
is the Pallas tile geometry — flash-attention block_q/block_k, fused-norm row
blocks, swiglu tile widths.  The tuner times candidate tiles ON DEVICE for a
given shape signature, persists winners per DEVICE KIND (v5e and v5p disagree
on the best tiles; a cache tuned on one must not silently apply to the
other), and the kernels consult the cache at trace time — so the
`PallasFusionPass` substitutions pick tuned tiles automatically with zero
call-site changes.

Layout:
- checked-in seed caches: `paddle_tpu/ops/tuned/<device_kind_slug>.json`
- runtime-tuned entries merge over the seed and save to
  `FLAGS_autotune_cache_dir` (defaults to the seed dir; falls back to
  `~/.cache/paddle_tpu/autotune` when unwritable)
- `python -m paddle_tpu.ops.autotune --kernel all` sweeps the standard
  shape set within a time budget and writes the cache.
"""

from __future__ import annotations

import json
import os
import time

__all__ = [
    "AutotuneCache",
    "cache",
    "lookup",
    "record",
    "tune_kernel",
    "tune_flash",
    "tune_fused_norm",
    "tune_swiglu",
    "device_kind_slug",
    "flash_vmem_bytes",
    "validate_tile",
    "validate_flash_tile",
]

_VMEM_BUDGET = 16 << 20  # ~16 MB/core on every current TPU generation

# Format marker written into runtime cache files so loads can tell a
# post-fix runtime delta (runtime-wins contract applies) from a pre-fix
# seed-merged dump (healed at load: seeded keys dropped).
_RUNTIME_MARKER = "__paddle_tpu_runtime__"


def device_kind_slug(device=None):
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or device.platform
    return "".join(c if c.isalnum() else "_" for c in kind.lower()).strip("_")


def _key_str(key: dict) -> str:
    return "|".join(f"{k}={key[k]}" for k in sorted(key))


class AutotuneCache:
    """Per-device-kind persistent (kernel, shape-key) -> config cache."""

    def __init__(self, slug=None):
        self.slug = slug or device_kind_slug()
        self._data: dict = {}
        self._dirty = False
        self._load()

    # ------------------------------------------------------------- paths
    @property
    def seed_path(self):
        return os.path.join(os.path.dirname(__file__), "tuned", f"{self.slug}.json")

    def _save_path(self):
        from paddle_tpu._core import flags as _flags

        d = str(_flags.flag("FLAGS_autotune_cache_dir") or "")
        if d:
            return os.path.join(d, f"{self.slug}.json")
        return self.seed_path

    @property
    def user_path(self):
        """Fallback written when the package dir is read-only — also read
        back at load time, newest-priority."""
        return os.path.join(os.path.expanduser("~/.cache/paddle_tpu/autotune"),
                            f"{self.slug}.json")

    def _load(self):
        # priority (last wins): seed < user fallback < explicitly configured
        # dir; when no dir is configured _save_path() IS the seed path —
        # dedupe so the seed cannot re-apply over newer user entries.
        # Seed-originated and runtime entries are tracked separately: the
        # runtime save must NOT fossilize a copy of the seed into the
        # configured dir, or a later package seed update for a key the
        # runtime never tuned would be silently shadowed by the stale copy.
        self._runtime: dict = {}
        paths = [(self.seed_path, False), (self.user_path, True)]
        sp = self._save_path()
        if sp not in (self.seed_path, self.user_path):
            paths.append((sp, True))
        seed: dict = {}
        for path, is_runtime in paths:
            try:
                with open(path) as f:
                    loaded = json.load(f)
            except (OSError, ValueError):
                continue
            marked = bool(loaded.pop(_RUNTIME_MARKER, None))
            for kernel, entries in loaded.items():
                if is_runtime and not marked:
                    # heal dumps written by the pre-marker save() (it
                    # copied the whole seed-merged table): a stale copy of
                    # a seed entry is value-indistinguishable from a
                    # genuine retune once the seed updates, so an UNMARKED
                    # runtime file keeps only keys the seed doesn't have —
                    # seeded keys re-tune once, stale copies can never
                    # shadow a seed update again
                    entries = {k: v for k, v in entries.items()
                               if k not in seed.get(kernel, {})}
                elif is_runtime:
                    # marked (post-fix) file: runtime wins per contract;
                    # entries identical to the seed carry no information
                    entries = {k: v for k, v in entries.items()
                               if seed.get(kernel, {}).get(k) != v}
                if is_runtime:
                    self._runtime.setdefault(kernel, {}).update(entries)
                else:
                    seed.setdefault(kernel, {}).update(entries)
                self._data.setdefault(kernel, {}).update(entries)

    def save(self):
        if not self._dirty:
            return None
        path = self._save_path()
        for candidate in (path, self.user_path):
            # writing INTO the seed file keeps its seed entries (merged
            # payload); any runtime location gets runtime entries only,
            # tagged with the format marker so reloads trust them
            if candidate == self.seed_path:
                payload = self._data
            else:
                payload = dict(self._runtime)
                payload[_RUNTIME_MARKER] = 1
            try:
                os.makedirs(os.path.dirname(candidate), exist_ok=True)
                with open(candidate, "w") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                self._dirty = False
                return candidate
            except OSError:
                continue
        return None

    # ------------------------------------------------------------- access
    def get(self, kernel: str, key: dict):
        entry = self._data.get(kernel, {}).get(_key_str(key))
        return dict(entry["config"]) if entry else None

    def put(self, kernel: str, key: dict, config: dict, ms: float, meta=None):
        entry = {
            "config": dict(config),
            "ms": round(float(ms), 6),
            **({"meta": meta} if meta else {}),
        }
        self._data.setdefault(kernel, {})[_key_str(key)] = entry
        self._runtime.setdefault(kernel, {})[_key_str(key)] = dict(entry)
        self._dirty = True


_CACHES: dict = {}


def cache(slug=None) -> AutotuneCache:
    from paddle_tpu._core import flags as _flags

    slug = slug or device_kind_slug()
    # keyed on the configured dir too: changing FLAGS_autotune_cache_dir
    # after a lookup must take effect, not be silently memoized away
    key = (slug, str(_flags.flag("FLAGS_autotune_cache_dir") or ""))
    if key not in _CACHES:
        _CACHES[key] = AutotuneCache(slug)
    return _CACHES[key]


def lookup(kernel: str, key: dict, slug=None):
    """Cache consultation used by the kernels at trace time; None when the
    shape was never tuned on this device kind (or the cache is disabled)."""
    from paddle_tpu._core import flags as _flags

    if not _flags.flag("FLAGS_use_autotune_cache"):
        return None
    try:
        return cache(slug).get(kernel, key)
    except Exception:
        return None


def record(kernel, key, config, ms, slug=None, save=True):
    c = cache(slug)
    c.put(kernel, key, config, ms)
    if save:
        c.save()
    return c


# ---------------------------------------------------------------------------
# measurement


def _time_fn(fn, args, warmup=1, iters=3, timer=None, inner=None,
             target_ms=300.0):
    """Estimate per-call device ms of fn(*args).

    The only true barrier on the remote transport is a device→host
    readback (see paddle_tpu.device.hard_sync — block_until_ready
    resolves at dispatch), and that round trip is both large (~tens of
    ms) and NOISY (±tens of ms), so neither per-call timing nor a
    fixed-length difference survives it.  Methodology:

    1. measure the pure readback round trip on an already-ready array;
    2. pilot-run a short batch to rough-estimate the per-call cost;
    3. size `inner` so one batch costs ~`target_ms` of device time —
       the RTT noise then perturbs the estimate by noise/target only;
    4. per sample, time `inner` and `2*inner` back-to-back dispatches
       and difference the totals: the constant readback + dispatch
       latency cancels, leaving inner * kernel_ms.  Median over iters.

    Pass `inner` explicitly to skip the adaptive sizing (tests)."""
    import jax.numpy as jnp

    from paddle_tpu.device import hard_sync

    if timer is not None:  # deterministic tests inject a fake timer
        return timer(fn, args)
    for _ in range(warmup):
        hard_sync(fn(*args))

    def total_ms(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        hard_sync(out)
        return (time.perf_counter() - t0) * 1e3

    if inner is None:
        ready = jnp.zeros(8)
        hard_sync(ready)
        rtt_samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            hard_sync(ready)
            rtt_samples.append((time.perf_counter() - t0) * 1e3)
        rtt = min(rtt_samples)
        pilot = total_ms(8)
        per_call = max((pilot - rtt) / 8, 1e-3)
        inner = int(min(max(target_ms / per_call, 8), 4096))

    times = []
    for _ in range(iters):
        cur = inner
        for _attempt in range(3):
            t1 = total_ms(cur)
            t2 = total_ms(2 * cur)
            diff = (t2 - t1) / cur
            if diff > 1e-4:
                times.append(diff)
                break
            # RTT noise swamped the signal: a nonpositive difference is a
            # FAILED sample, never a result — grow the batch and retry
            # (silently clamping here once shipped noise-picked tiles)
            cur = min(cur * 4, 8192)
        else:
            import warnings

            warnings.warn(
                "autotune: timing sample degenerate even at inner=%d "
                "(readback RTT noise exceeds the kernel signal)" % cur)
    if not times:
        raise RuntimeError(
            "autotune: every timing sample was degenerate — transport too "
            "noisy to rank candidates; not recording a winner")
    times.sort()
    return times[len(times) // 2]


def tune_kernel(kernel, key, build, candidates, args, *, iters=3, inner=None,
                budget_s=None, timer=None, slug=None, save=True, verbose=False):
    """Search `candidates` (list of config dicts) for the fastest
    `build(config)(*args)`; record and return (best_config, best_ms).

    Invalid configs (build or execution raises) are skipped — an exhausted
    candidate list raises so tuning failures are loud, not silent."""
    best_cfg, best_ms = None, float("inf")
    t_start = time.perf_counter()
    for cfg in candidates:
        if budget_s is not None and time.perf_counter() - t_start > budget_s and best_cfg is not None:
            break
        try:
            fn = build(cfg)
            ms = _time_fn(fn, args, iters=iters, timer=timer, inner=inner)
        except Exception as e:  # noqa: BLE001 — candidate invalid on this device
            if verbose:
                print(f"  {kernel} {cfg}: invalid ({type(e).__name__})")
            continue
        if verbose:
            print(f"  {kernel} {cfg}: {ms:.3f} ms")
        if ms < best_ms:
            best_cfg, best_ms = dict(cfg), ms
    if best_cfg is None:
        raise RuntimeError(
            f"autotune: no valid candidate for {kernel} {_key_str(key)} "
            f"out of {len(list(candidates))}")
    record(kernel, key, best_cfg, best_ms, slug=slug, save=save)
    return best_cfg, best_ms


# ---------------------------------------------------------------------------
# per-kernel candidate spaces + drivers


def flash_vmem_bytes(block_q, block_k, seq_k, head_dim):
    """fp32 working-set estimate for one fwd grid step (double-buffered
    pipeline): whole-K/V residency + q/o blocks + the scores tile."""
    per = (
        2 * seq_k * head_dim        # k + v (full sequence per (b, n))
        + 2 * block_q * head_dim    # q + o
        + block_q * block_k         # scores/probs tile
        + block_q * 128             # lse lane padding
    )
    return per * 4 * 2


def validate_tile(vmem_bytes, budget=None):
    """Generic VMEM-budget check for any candidate tiling: None when a
    working-set estimate fits the per-core budget, else a human-readable
    reason.  The kernel-specific validators (validate_flash_tile) and the
    schedule searcher's candidate prune (static/schedule_search.py) share
    this single budget definition."""
    b = _VMEM_BUDGET if budget is None else int(budget)
    need = int(vmem_bytes)
    if need > b:
        return (f"working set ~{max(need >> 20, 1)} MiB VMEM "
                f"> {b >> 20} MiB budget")
    return None


def validate_flash_tile(block_q, block_k, seq_q, seq_k, head_dim):
    """None when valid; else a human-readable reason (kernels warn with it
    rather than silently falling back — VERDICT r3 #10)."""
    if block_q < 8 or block_q % 8:
        return f"block_q={block_q} must be a positive multiple of 8"
    if block_k < 8 or block_k % 8:
        return f"block_k={block_k} must be a positive multiple of 8"
    if seq_q % block_q:
        return f"block_q={block_q} does not divide seq_q={seq_q}"
    if seq_k % block_k:
        return f"block_k={block_k} does not divide seq_k={seq_k}"
    reason = validate_tile(flash_vmem_bytes(block_q, block_k, seq_k, head_dim))
    if reason:
        return f"tile ({block_q},{block_k}): {reason}"
    return None


def flash_candidates(seq_q, seq_k, head_dim):
    sizes = (64, 128, 256, 512)
    out = []
    for bq in sizes:
        for bk in sizes:
            if validate_flash_tile(bq, bk, seq_q, seq_k, head_dim) is None:
                out.append({"block_q": bq, "block_k": bk})
    return out


def tune_flash(batch=1, num_heads=8, seq=2048, head_dim=128, dtype="bfloat16",
               causal=True, **kw):
    """Tune flash-attention fwd tiles for one shape signature."""
    import jax
    import jax.numpy as jnp

    import importlib

    # NOT `from paddle_tpu.ops import flash_attention`: the package exports a
    # *function* named flash_attention that shadows the submodule attribute.
    fa = importlib.import_module("paddle_tpu.ops.flash_attention")

    jd = jnp.dtype(dtype)
    key = {"seq_q": seq, "seq_k": seq, "head_dim": head_dim,
           "dtype": jd.name, "causal": bool(causal)}
    rng = jax.random.PRNGKey(0)
    qkv = [
        jax.random.normal(k, (batch, num_heads, seq, head_dim), jd)
        for k in jax.random.split(rng, 3)
    ]

    def build(cfg):
        f = jax.jit(lambda q, k, v: fa._flash_bnsh(
            q, k, v, 1.0 / head_dim ** 0.5, causal,
            cfg["block_q"], cfg["block_k"]))
        return f

    return tune_kernel("flash_fwd", key, build,
                       flash_candidates(seq, seq, head_dim), qkv, **kw)


def norm_candidates(rows, hidden):
    out = []
    for br in (8, 16, 32, 64, 128, 256, 512):
        if br <= rows and rows % br == 0 and br * hidden * 4 * 2 <= _VMEM_BUDGET:
            out.append({"rows_block": br})
    return out or [{"rows_block": rows}]


def tune_fused_norm(rows=4096, hidden=4096, dtype="bfloat16", **kw):
    import jax
    import jax.numpy as jnp

    import importlib

    fnorm = importlib.import_module("paddle_tpu.ops.fused_norm")

    jd = jnp.dtype(dtype)
    key = {"rows": rows, "hidden": hidden, "dtype": jd.name}
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, hidden), jd)
    w = jax.random.normal(jax.random.PRNGKey(1), (hidden,), jd)

    def build(cfg):
        import functools

        br = cfg["rows_block"]

        def run(x, w):
            return fnorm._pallas_rows(
                functools.partial(fnorm._rms_kernel, eps=1e-6), x, (w,),
                x.dtype, rows_block=br)

        return jax.jit(run)

    return tune_kernel("rms_rows", key, build, norm_candidates(rows, hidden),
                       (x, w), **kw)


def swiglu_candidates(rows, cols):
    out = []
    for br in (64, 128, 256, 512):
        for bc in (128, 256, 512, 1024, 2048):
            if (br <= rows and rows % br == 0 and bc <= cols and cols % bc == 0
                    and br * bc * 4 * 3 * 2 <= _VMEM_BUDGET):
                out.append({"rows_block": br, "cols_block": bc})
    return out or [{"rows_block": rows, "cols_block": cols}]


def tune_swiglu(rows=4096, cols=11008, dtype="bfloat16", **kw):
    import jax
    import jax.numpy as jnp

    import importlib

    # see tune_flash: the swiglu function shadows its submodule on the package
    sw = importlib.import_module("paddle_tpu.ops.swiglu")

    jd = jnp.dtype(dtype)
    key = {"rows": rows, "cols": cols, "dtype": jd.name}
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, cols), jd)
    y = jax.random.normal(jax.random.PRNGKey(1), (rows, cols), jd)

    def build(cfg):
        return jax.jit(lambda a, b: sw._swiglu_apply(
            a, b, rows_block=cfg["rows_block"], cols_block=cfg["cols_block"]))

    return tune_kernel("swiglu", key, build, swiglu_candidates(rows, cols),
                       (x, y), **kw)


def matmul_epilogue_candidates(M, K, N):
    out = []
    for bm in (128, 256, 512):
        for bn in (128, 256):
            for bk in (256, 512, 1024):
                if (bm <= M and M % bm == 0 and bn <= N and N % bn == 0
                        and bk <= K and K % bk == 0
                        # f32 acc + double-buffered in/out blocks
                        and (bm * bn * 4 + 2 * (bm * bk + bk * bn + bm * bn) * 2)
                        <= _VMEM_BUDGET):
                    out.append({"bm": bm, "bk": bk, "bn": bn})
    return out or [{"bm": min(M, 128), "bk": K, "bn": min(N, 128)}]


def tune_matmul_epilogue(m=4096, k=4096, n=4096, dtype="bfloat16", **kw):
    import jax
    import jax.numpy as jnp

    import importlib

    me = importlib.import_module("paddle_tpu.ops.matmul_epilogue")

    jd = jnp.dtype(dtype)
    key = {"m": m, "k": k, "n": n, "dtype": jd.name}
    x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jd)
    w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jd)
    b = jax.random.normal(jax.random.PRNGKey(2), (n,), jd)

    def build(cfg):
        tiles = (cfg["bm"], cfg["bk"], cfg["bn"])
        return jax.jit(lambda a, ww, bb: me._fused_2d(a, ww, bb, "gelu",
                                                      tiles=tiles))

    return tune_kernel("matmul_epilogue", key, build,
                       matmul_epilogue_candidates(m, k, n), (x, w, b), **kw)


# ---------------------------------------------------------------------------
# CLI: bounded-time sweep over the standard shape set


# Flagship-first ordering: bench.py's hidden-2048/S=1024 LLaMA uses
# flash(seq=1024, hd=128), norm rows=B*1024 x 2048, swiglu rows x 5632 —
# a short on-chip budget tunes exactly those before the generic shapes.
_STANDARD_SHAPES = {
    "flash": [
        dict(seq=1024, head_dim=128), dict(seq=2048, head_dim=128),
        dict(seq=4096, head_dim=128), dict(seq=2048, head_dim=64),
    ],
    "norm": [
        dict(rows=4096, hidden=2048), dict(rows=8192, hidden=2048),
        dict(rows=16384, hidden=2048), dict(rows=4096, hidden=4096),
        dict(rows=8192, hidden=4096),
    ],
    "swiglu": [
        dict(rows=4096, cols=5632), dict(rows=8192, cols=5632),
        dict(rows=16384, cols=5632), dict(rows=4096, cols=11008),
    ],
    "matmul": [
        dict(m=4096, k=2048, n=8192), dict(m=4096, k=4096, n=4096),
        dict(m=8192, k=2048, n=2048),
    ],
}


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Pallas kernel tile autotuner")
    p.add_argument("--kernel", default="all",
                   choices=["all", "flash", "norm", "swiglu", "matmul"])
    p.add_argument("--budget-seconds", type=float, default=300.0,
                   help="total wall budget; stops between candidates")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--inner", type=int, default=None,
                   help="dispatches per timing sample (default: adaptive — "
                        "sized so one sample is ~300ms of device time; the "
                        "RTT-cancelling difference times inner and 2*inner)")
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    slug = device_kind_slug()
    print(f"tuning for device kind: {slug}")
    runners = {"flash": tune_flash, "norm": tune_fused_norm,
               "swiglu": tune_swiglu, "matmul": tune_matmul_epilogue}
    todo = [args.kernel] if args.kernel != "all" else list(runners)
    for name in todo:
        for shape in _STANDARD_SHAPES[name]:
            left = args.budget_seconds - (time.perf_counter() - t0)
            if left <= 0:
                print("budget exhausted")
                break
            cfg, ms = runners[name](dtype=args.dtype, budget_s=left, verbose=True,
                                    inner=args.inner, **shape)
            print(f"{name} {shape}: best {cfg} @ {ms:.3f} ms")
    path = cache(slug).save()
    print(f"cache written: {path}")


if __name__ == "__main__":
    main()
