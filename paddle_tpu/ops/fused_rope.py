"""Fused rotary position embedding.

Reference: paddle.incubate.nn.functional.fused_rotary_position_embedding
(paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu).  The reference fuses
the interleaved-pair rotation into one CUDA kernel; on TPU the rotation is a
pure elementwise chain that XLA fuses into the surrounding matmuls on its
own, so the TPU-native implementation is jnp with a hand-written inverse
VJP (rotation matrices are orthogonal — the backward is the same rotation
with negated sin, cheaper than the autodiff transpose and recompute-free).

A Pallas kernel was deliberately NOT used here: the interleaved pair layout
requires splitting the 128-lane minor dimension ([.., H] -> [.., H/2, 2]),
a shape cast Mosaic cannot lower (infer-vector-layout: unsupported shape
cast), and rope is bandwidth-bound so a kernel buys nothing over XLA fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _rope_apply(x, cos_r, sin_r):
    """x: [B, S, N, H]; cos_r/sin_r: per-token tables [B*S, H/2] fp32."""
    b, s, n, h = x.shape
    xf = x.astype(jnp.float32).reshape(b * s, n, h // 2, 2)
    c = cos_r[:, None, :]
    sn = sin_r[:, None, :]
    x1 = xf[..., 0]
    x2 = xf[..., 1]
    r1 = x1 * c - x2 * sn
    r2 = x2 * c + x1 * sn
    out = jnp.stack([r1, r2], axis=-1).reshape(b, s, n, h)
    return out.astype(x.dtype)


@jax.custom_vjp
def _rope(x, cos, sin):
    return _rope_apply(x, cos, sin)


def _rope_fwd(x, cos, sin):
    return _rope_apply(x, cos, sin), (cos, sin)


def _rope_bwd(res, g):
    cos, sin = res
    return _rope_apply(g, cos, -sin), None, None


_rope.defvjp(_rope_fwd, _rope_bwd)


def fused_rotary_position_embedding(q, k=None, v=None, *, cos, sin, position_offset=0, position_ids=None):
    """Rotate q (and k) with interleaved-pair RoPE.  q/k: [B, S, N, H];
    cos/sin: [max_len, H/2] fp32 tables.  position_ids [B, S] (packed or
    left-padded sequences) selects per-token table rows; otherwise absolute
    position + offset is used.  v passes through (parity with the reference
    signature which optionally rotates v — rarely used)."""
    b, s = q.shape[0], q.shape[1]
    if position_ids is not None:
        c = jnp.take(cos, position_ids.reshape(-1), axis=0)
        sn = jnp.take(sin, position_ids.reshape(-1), axis=0)
    else:
        c = jax.lax.dynamic_slice_in_dim(cos, position_offset, s, axis=0)
        sn = jax.lax.dynamic_slice_in_dim(sin, position_offset, s, axis=0)
        c = jnp.tile(c, (b, 1))
        sn = jnp.tile(sn, (b, 1))
    outs = [_rope(q, c, sn)]
    if k is not None:
        outs.append(_rope(k, c, sn))
    if v is not None:
        outs.append(v)
    return outs[0] if len(outs) == 1 else tuple(outs)
