"""Fused rotary position embedding (Pallas).

Reference: paddle.incubate.nn.functional.fused_rotary_position_embedding
(paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu).  One VPU kernel rotates
q and k in-place-style per (batch, seq-block); backward is the inverse
rotation (rotation matrices are orthogonal), implemented with the same kernel
run with negated sin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops._pl_utils import imap


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    # x: [bs, N*H] viewed rows; cos/sin: [bs, H/2]
    x = x_ref[:].astype(jnp.float32)
    bs, nh = x.shape
    half = cos_ref.shape[-1]
    n = nh // (2 * half)
    x = x.reshape(bs, n, half, 2)
    c = cos_ref[:].astype(jnp.float32)[:, None, :]
    s = sin_ref[:].astype(jnp.float32)[:, None, :]
    x1 = x[..., 0]
    x2 = x[..., 1]
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    out = jnp.stack([r1, r2], axis=-1).reshape(bs, nh)
    o_ref[:] = out.astype(o_ref.dtype)


def _rope_apply(x, cos_r, sin_r):
    """x: [B, S, N, H]; cos_r/sin_r: per-token tables [B*S, H/2] fp32."""
    b, s, n, h = x.shape
    x2d = x.reshape(b * s, n * h)
    bs = min(256, b * s)
    if (b * s) % bs:
        bs = b * s
    out = pl.pallas_call(
        _rope_kernel,
        grid=((b * s) // bs,),
        in_specs=[
            pl.BlockSpec((bs, n * h), imap(lambda i: (i, 0))),
            pl.BlockSpec((bs, h // 2), imap(lambda i: (i, 0))),
            pl.BlockSpec((bs, h // 2), imap(lambda i: (i, 0))),
        ],
        out_specs=pl.BlockSpec((bs, n * h), imap(lambda i: (i, 0))),
        out_shape=jax.ShapeDtypeStruct((b * s, n * h), x.dtype),
        interpret=jax.default_backend() != "tpu",
    )(x2d, cos_r, sin_r)
    return out.reshape(b, s, n, h)


@jax.custom_vjp
def _rope(x, cos, sin):
    return _rope_apply(x, cos, sin)


def _rope_fwd(x, cos, sin):
    return _rope_apply(x, cos, sin), (cos, sin)


def _rope_bwd(res, g):
    cos, sin = res
    return _rope_apply(g, cos, -sin), None, None


_rope.defvjp(_rope_fwd, _rope_bwd)


def fused_rotary_position_embedding(q, k=None, v=None, *, cos, sin, position_offset=0, position_ids=None):
    """Rotate q (and k) with interleaved-pair RoPE.  q/k: [B, S, N, H];
    cos/sin: [max_len, H/2] fp32 tables.  position_ids [B, S] (packed or
    left-padded sequences) selects per-token table rows; otherwise absolute
    position + offset is used.  v passes through (parity with the reference
    signature which optionally rotates v — rarely used)."""
    b, s = q.shape[0], q.shape[1]
    half = cos.shape[-1]
    if position_ids is not None:
        c = jnp.take(cos, position_ids.reshape(-1), axis=0)
        sn = jnp.take(sin, position_ids.reshape(-1), axis=0)
    else:
        c = jax.lax.dynamic_slice_in_dim(cos, position_offset, s, axis=0)
        sn = jax.lax.dynamic_slice_in_dim(sin, position_offset, s, axis=0)
        c = jnp.tile(c, (b, 1))
        sn = jnp.tile(sn, (b, 1))
    outs = [_rope(q, c, sn)]
    if k is not None:
        outs.append(_rope(k, c, sn))
    if v is not None:
        outs.append(v)
    return outs[0] if len(outs) == 1 else tuple(outs)
