"""Fused RMSNorm / LayerNorm Pallas kernels.

Reference: paddle.incubate.nn.functional.fused_rms_norm / fused_layer_norm
(paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu).  TPU-native: one
VMEM-resident rowwise kernel computing fp32 statistics and the scaled output
in a single pass; backward is analytic jnp (XLA fuses it into the surrounding
backward graph).  Supports the reference's residual-add fusion
(`fused_layer_norm(x, residual=...)` adds before normalizing and returns the
pre-norm sum as well).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops._pl_utils import imap


def _rows_block(total_rows, hidden=1024, dtype=None):
    # 1. autotune cache (per device kind; ops/autotune.py)
    from paddle_tpu.ops import autotune as _at

    tuned = _at.lookup("rms_rows", {
        "rows": total_rows, "hidden": hidden,
        "dtype": jnp.dtype(dtype).name if dtype is not None else "bfloat16"})
    if tuned:
        br = int(tuned["rows_block"])
        if 0 < br <= total_rows and total_rows % br == 0:
            return br
    # 2. analytic default: bound the double-buffered VMEM footprint — the
    # kernel holds the block in f32 (4B) for the reduction, so keep
    # br*hidden*4 around <=4MB, and br a multiple of 8 (f32 sublane).
    cap = max(8, (4 << 20) // max(1, hidden * 4))
    cap -= cap % 8 or 0
    return min(max(cap, 8), 256, total_rows)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + jnp.float32(eps))
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + jnp.float32(eps))
    o_ref[:] = (xc * inv * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _pallas_rows(kernel, x2d, params, out_dtype, rows_block=None):
    rows, hidden = x2d.shape
    br = rows_block or _rows_block(rows, hidden, x2d.dtype)
    if rows % br:
        br = rows  # small/ragged: single block
    grid = (rows // br,)
    in_specs = [pl.BlockSpec((br, hidden), imap(lambda i: (i, 0)))]
    in_specs += [pl.BlockSpec((hidden,), imap(lambda i: (0,))) for _ in params]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, hidden), imap(lambda i: (i, 0))),
        out_shape=jax.ShapeDtypeStruct((rows, hidden), out_dtype),
        interpret=jax.default_backend() != "tpu",
    )(x2d, *params)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms(x2d, w, eps):
    return _pallas_rows(functools.partial(_rms_kernel, eps=eps), x2d, (w,), x2d.dtype)


def _rms_fwd(x2d, w, eps):
    return _rms(x2d, w, eps), (x2d, w)


def _rms_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32) * w.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    # d/dx [x * inv]: inv * g - x * (x.g) * inv^3 / H
    h = x.shape[-1]
    dot = jnp.sum(gf * xf, axis=-1, keepdims=True)
    dx = (gf * inv - xf * dot * inv**3 / h).astype(x.dtype)
    dw = jnp.sum(g.astype(jnp.float32) * (xf * inv), axis=0).astype(w.dtype)
    return dx, dw


_rms.defvjp(_rms_fwd, _rms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln(x2d, w, b, eps):
    return _pallas_rows(functools.partial(_ln_kernel, eps=eps), x2d, (w, b), x2d.dtype)


def _ln_fwd(x2d, w, b, eps):
    return _ln(x2d, w, b, eps), (x2d, w)


def _ln_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    h = x.shape[-1]
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xc * inv
    gf = g.astype(jnp.float32)
    gw = gf * w.astype(jnp.float32)
    dx = inv * (gw - jnp.mean(gw, axis=-1, keepdims=True) - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype)
    db = jnp.sum(gf, axis=0).astype(w.dtype)
    return dx.astype(x.dtype), dw, db


_ln.defvjp(_ln_fwd, _ln_bwd)


def fused_rms_norm(x, weight, *, epsilon=1e-6, residual=None):
    """RMSNorm over the last axis; optional fused residual add.

    Returns `out` or `(out, x_plus_residual)` when residual is given —
    matching the reference wrapper's contract
    (python/paddle/incubate/nn/functional/fused_rms_norm.py).
    """
    if residual is not None:
        x = x + residual
    shape = x.shape
    out = _rms(x.reshape(-1, shape[-1]), weight, float(epsilon)).reshape(shape)
    if residual is not None:
        return out, x
    return out


def fused_layer_norm(x, weight, bias, *, epsilon=1e-5, residual=None):
    if residual is not None:
        x = x + residual
    shape = x.shape
    if bias is None:
        bias = jnp.zeros(shape[-1], dtype=x.dtype)
    out = _ln(x.reshape(-1, shape[-1]), weight, bias, float(epsilon)).reshape(shape)
    if residual is not None:
        return out, x
    return out
