"""Paged-KV (block) attention for serving.

Reference: the block attention serving tier —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and
python/paddle/incubate/nn/functional/block_multihead_attention.py: the KV
cache is a pool of fixed-size blocks; each sequence owns a block table
mapping its logical positions onto pool blocks, so cache memory is allocated
per-16-token page instead of per-max-seq-len (vLLM-style paging).

TPU-native design: the pool is ONE [num_blocks, Nkv, block_size, H] array per
K and V; block writes are scatter-at-index updates and decode attention
gathers each sequence's pages with jnp.take on the block table.  Both lower
to XLA dynamic-scatter/gather which on TPU are HBM-bandwidth-bound copies —
the same roofline the hand-written CUDA kernel targets — and the whole
decode step (gather + QK^T + softmax + PV) fuses into one executable.
Everything is shape-static: max_blocks_per_seq bounds the gather and a
length mask handles raggedness, so the step jits once and is reused for the
whole decode.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = [
    "QuantPool",
    "alloc_paged_cache",
    "paged_write",
    "paged_write_chunk",
    "paged_pour_blocks",
    "paged_pour_block",
    "paged_gather",
    "gathered_attention",
    "paged_decode_attention",
    "paged_chunk_attention",
    "pool_num_kv_heads",
    "pool_nbytes",
    "pool_device_nbytes",
    "pool_parts",
    "pool_state_dict",
    "pool_from_state",
    "pool_get_blocks",
    "pool_set_blocks",
    "pool_stack",
    "pool_index",
]

_QMAX = 127.0  # symmetric int8 range; -128 is never produced
_EPS = 1e-12


@jax.tree_util.register_pytree_node_class
class QuantPool:
    """Int8-quantized paged pool: `data` int8 [num_blocks, Nkv, bs, H] plus
    per-block-per-head `scale` float32 [num_blocks, Nkv].

    A stored element decodes as ``data * scale`` (symmetric, zero-point
    free).  Scales are running maxima per (block, head): a decode write
    whose amax exceeds the block's current scale grows the scale and
    RESCALES the block's existing payload against it (one small gather +
    scatter over just the touched blocks, inside the jitted step), so every
    resident token stays decodable with the single per-block scale.  A
    deliberate pytree (NOT a tuple subclass): per-layer pool LISTS keep
    meaning "unstacked" in _decode_layers_paged, and jit / donate_argnums /
    lax.scan thread the (data, scale) pair as ordinary leaves.
    """

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    @property
    def nbytes(self):
        return self.data.nbytes + self.scale.nbytes


def pool_num_kv_heads(cache):
    """Nkv of a paged pool, quantized or plain."""
    return (cache.data if isinstance(cache, QuantPool) else cache).shape[1]


def pool_nbytes(cache):
    """Resident bytes of a paged pool (payload + scales for QuantPool)."""
    return cache.nbytes


def pool_device_nbytes(cache):
    """PER-DEVICE resident bytes of a paged pool: each leaf's committed
    sharding divides its global bytes (``shard_shape``); uncommitted or
    single-device leaves count whole.  The serving telemetry's
    ``pool_bytes_per_device`` (and the mesh lint's per-device HBM
    estimate) see the TP-sharded engine's true per-chip footprint through
    this — a KV-head-sharded pool on an mp=4 mesh reports a quarter of
    ``pool_nbytes`` here."""
    total = 0
    for _name, arr in pool_parts(cache):
        shape = arr.shape
        sharding = getattr(arr, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(arr.shape)
            except (TypeError, ValueError):
                pass  # abstract/placeholder leaf: count it whole
        total += math.prod(shape) * arr.dtype.itemsize
    return total


def pool_parts(cache):
    """[(part_name, array)] leaves of a paged pool — ('payload', data) for
    a plain pool, plus ('scale', scales) for a QuantPool.  The ONE place
    that knows QuantPool's structure for per-leaf consumers (the mesh
    lint's placement/byte accounting walks pools through this, so an
    added QuantPool field is automatically covered there)."""
    if isinstance(cache, QuantPool):
        return [("payload", cache.data), ("scale", cache.scale)]
    return [("payload", cache)]


def pool_state_dict(prefix, cache):
    """Flat ``{f"{prefix}.{part}": array}`` view of a paged pool's leaves —
    the serialization face of `pool_parts` (engine snapshots feed these
    names to the sharded checkpoint store; serving/snapshot.py).  A
    QuantPool contributes its payload AND scales, so a serialized int8
    pool round-trips bit-exactly."""
    return {f"{prefix}.{name}": arr for name, arr in pool_parts(cache)}


def pool_from_state(template, fetch, prefix=""):
    """Rebuild a pool shaped like `template` by calling
    ``fetch(f"{prefix}.{part}", template_leaf)`` per leaf — the inverse of
    `pool_state_dict`.  `fetch` returns the restored array for that leaf
    (the caller owns assembly/resharding/placement); the ONE other place
    that knows QuantPool's structure, so an added field breaks both
    directions loudly together."""
    if isinstance(template, QuantPool):
        return QuantPool(fetch(f"{prefix}.payload", template.data),
                         fetch(f"{prefix}.scale", template.scale))
    return fetch(f"{prefix}.payload", template)


def pool_get_blocks(cache, block_ids):
    """Native-format page extraction — the wire face of `pool_parts` for
    cross-process KV shipping (serving/cluster.py): the pool's OWN leaves
    at `block_ids`, as ``{"payload": [n, Nkv, bs, H]}`` plus
    ``{"scale": [n, Nkv]}`` for a QuantPool.  An int8 pool ships its int8
    payload and f32 scales VERBATIM (about half the wire bytes of a bf16
    pool), and `pool_set_blocks` on the receiving side places the same
    bytes — ship-then-place is bit-exact by construction, never a
    re-quantization."""
    idx = jnp.asarray(block_ids, jnp.int32)
    return {name: jnp.take(arr, idx, axis=0)
            for name, arr in pool_parts(cache)}


def pool_set_blocks(cache, block_ids, blocks):
    """Place native-format pages (a `pool_get_blocks` dict) into the pool
    at `block_ids`.  The inverse wire face: leaves land verbatim (cast
    only to the pool leaf dtype, an identity for a matched pool kind) —
    quantization happened on the sending side or not at all."""
    idx = jnp.asarray(block_ids, jnp.int32)
    if isinstance(cache, QuantPool):
        return QuantPool(
            cache.data.at[idx].set(
                jnp.asarray(blocks["payload"], cache.data.dtype)),
            cache.scale.at[idx].set(
                jnp.asarray(blocks["scale"], cache.scale.dtype)))
    return cache.at[idx].set(jnp.asarray(blocks["payload"], cache.dtype))


def pool_stack(pools):
    """Per-layer pool list -> ONE stacked [N, ...] pool (leaf-wise, so a
    list of QuantPools stacks into a QuantPool of stacked leaves)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *pools)


def pool_index(pool, i):
    """Layer i's pool out of a stacked [N, ...] pool (leaf-wise)."""
    return jax.tree_util.tree_map(lambda x: x[i], pool)


def rope_rotate_by_position(t, cos, sin, positions):
    """Interleaved-pair rotation of per-token heads by gathered positions.

    t: [B, N, H]; cos/sin: [max_len, H/2] tables; positions: [B] int32.
    The SINGLE rope implementation for decode paths (model prefill uses the
    same pair convention in models/llama.py apply_rotary_pos_emb) — change
    rope semantics here and there together.
    """
    # the T=1 case of rope_rotate_chunk — ONE implementation of the pair
    # convention (change rope semantics there, not here)
    return rope_rotate_chunk(t[:, None], cos, sin, positions[:, None])[:, 0]


def alloc_paged_cache(num_blocks, num_kv_heads, block_size, head_dim, dtype=jnp.bfloat16):
    """One K and one V pool: [num_blocks, Nkv, block_size, H].

    dtype 'int8' (or jnp.int8) allocates QuantPool pairs instead — int8
    payload plus per-block-per-head float32 scales (FLAGS_kv_cache_dtype).
    """
    shape = (num_blocks, num_kv_heads, block_size, head_dim)
    if jnp.dtype(dtype) == jnp.int8:
        def _one():
            return QuantPool(jnp.zeros(shape, jnp.int8),
                             jnp.zeros((num_blocks, num_kv_heads), jnp.float32))

        return _one(), _one()
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_write(cache, new, block_tables, positions):
    """Write one token per sequence into its page.

    cache: [num_blocks, Nkv, bs, H]; new: [B, Nkv, H];
    block_tables: [B, max_blocks] int32; positions: [B] int32 (token index
    within the sequence).  Returns the updated cache.
    """
    # the T=1 case of paged_write_chunk — one scatter implementation
    return paged_write_chunk(cache, new[:, None], block_tables,
                             positions[:, None])


def paged_gather(cache, block_tables):
    """Materialize each sequence's logical cache view.

    cache: [num_blocks, Nkv, bs, H] (or QuantPool); block_tables:
    [B, max_blocks] -> [B, Nkv, max_blocks*bs, H].  Quantized pools
    DEQUANTIZE on gather (float32 out): the decode step reads int8 pages +
    scales from HBM and rescales in registers — the capacity win is in the
    resident bytes, not the gathered view.
    """
    if isinstance(cache, QuantPool):
        pages = jnp.take(cache.data, block_tables, axis=0)  # [B,mb,Nkv,bs,H]
        scales = jnp.take(cache.scale, block_tables, axis=0)  # [B,mb,Nkv]
        pages = pages.astype(jnp.float32) * scales[..., None, None]
    else:
        pages = jnp.take(cache, block_tables, axis=0)  # [B, mb, Nkv, bs, H]
    b, mb, nkv, bs, h = pages.shape
    return jnp.moveaxis(pages, 2, 1).reshape(b, nkv, mb * bs, h)


def paged_decode_attention(q, key_cache, value_cache, block_tables, seq_lens, *, scale=None):
    """Single-token decode attention over the paged cache.

    q: [B, N, H] (the new token's queries, rope already applied);
    key_cache/value_cache: [num_blocks, Nkv, bs, H]; block_tables:
    [B, max_blocks]; seq_lens: [B] VALID length (including the new token).
    GQA: N may be a multiple of Nkv.  Returns [B, N, H].
    """
    # the T=1 case of paged_chunk_attention — one masked-softmax
    # implementation for the decode tier
    return paged_chunk_attention(q[:, None], key_cache, value_cache,
                                 block_tables, seq_lens, scale=scale)[:, 0]


def rope_rotate_chunk(t, cos, sin, positions):
    """Chunk variant of rope_rotate_by_position: t [B, T, N, H],
    positions [B, T] int32."""
    b, tt, n, h = t.shape
    c = jnp.take(jnp.asarray(cos), positions, axis=0)[:, :, None, :]  # [B,T,1,H/2]
    s = jnp.take(jnp.asarray(sin), positions, axis=0)[:, :, None, :]
    t2 = t.astype(jnp.float32).reshape(b, tt, n, h // 2, 2)
    r1 = t2[..., 0] * c - t2[..., 1] * s
    r2 = t2[..., 1] * c + t2[..., 0] * s
    return jnp.stack([r1, r2], -1).reshape(b, tt, n, h).astype(t.dtype)


def paged_write_chunk(cache, new, block_tables, positions):
    """Write T tokens per sequence into their pages.

    cache: [num_blocks, Nkv, bs, H] (or QuantPool); new: [B, T, Nkv, H];
    positions: [B, T] int32 (token index within each sequence).  The [B, T]
    scatter is one advanced-indexing update — speculative verify writes its
    whole chunk in one shot."""
    if isinstance(cache, QuantPool):
        return _quant_write_chunk(cache, new, block_tables, positions)
    bs = cache.shape[2]
    block_idx = jnp.take_along_axis(block_tables, positions // bs, axis=1)  # [B,T]
    slot = positions % bs
    # advanced indexing on dims 0 and 2 with [B, T] index arrays puts the
    # broadcast [B, T] in front: value shape [B, T, Nkv, H] == new
    return cache.at[block_idx, :, slot, :].set(new)


def _quant_write_chunk(pool, new, block_tables, positions):
    """Quantized paged_write_chunk: per-block-per-head running-max scales.

    The incoming tokens' per-head amax grows each touched block's scale
    via scatter-max; blocks whose scale grew get their EXISTING int8
    payload rescaled against the new scale (gather + scatter over just the
    touched blocks — every gather below predates the scatters, so chunk
    rows landing in the same block compute identical rescale values and
    duplicate-index writes stay deterministic); the new tokens then
    quantize against the final scales and scatter into their slots."""
    bs = pool.data.shape[2]
    block_idx = jnp.take_along_axis(block_tables, positions // bs, axis=1)  # [B,T]
    slot = positions % bs
    af = new.astype(jnp.float32)                                 # [B,T,Nkv,H]
    tok_scale = jnp.max(jnp.abs(af), axis=-1) / _QMAX            # [B,T,Nkv]
    old_scale = pool.scale[block_idx]                            # [B,T,Nkv]
    scale = pool.scale.at[block_idx].max(tok_scale)
    new_scale = scale[block_idx]                                 # final per block
    safe = jnp.maximum(new_scale, _EPS)
    old_blocks = pool.data[block_idx].astype(jnp.float32)        # [B,T,Nkv,bs,H]
    ratio = jnp.where(new_scale > old_scale, old_scale / safe, 1.0)
    resc = jnp.clip(jnp.round(old_blocks * ratio[..., None, None]),
                    -_QMAX, _QMAX).astype(jnp.int8)
    data = pool.data.at[block_idx].set(resc)
    q = jnp.clip(jnp.round(af / safe[..., None]), -_QMAX, _QMAX).astype(jnp.int8)
    data = data.at[block_idx, :, slot, :].set(q)
    return QuantPool(data, scale)


def paged_pour_blocks(cache, kv, block_ids):
    """Pour whole blocks (prefill) into the pool at `block_ids`.

    kv: [n_blocks, Nkv, bs, H] float values.  Quantized pools compute
    fresh per-block-per-head scales over the poured content (SET, not
    running-max — a recycled block's stale scale dies here)."""
    idx = jnp.asarray(block_ids, jnp.int32)
    if isinstance(cache, QuantPool):
        af = kv.astype(jnp.float32)
        s = jnp.max(jnp.abs(af), axis=(2, 3)) / _QMAX            # [n, Nkv]
        safe = jnp.maximum(s, _EPS)
        q = jnp.clip(jnp.round(af / safe[:, :, None, None]),
                     -_QMAX, _QMAX).astype(jnp.int8)
        return QuantPool(cache.data.at[idx].set(q),
                         cache.scale.at[idx].set(s))
    return cache.at[idx].set(kv.astype(cache.dtype))


def paged_pour_block(cache, kv, block_id):
    """Pour ONE block — the chunked-prefill entry (interleaved prefill
    pours each prompt block as its chunk completes; serving docs/DECODE.md
    admission scheduler).

    kv: [Nkv, bs, H] float values.  Delegates to `paged_pour_blocks` with
    n=1, so a quantized pool's per-block-per-head scale is the amax of
    exactly this block's content — the SAME scale (and therefore the same
    int8 bytes) the batched atomic pour computes for the block, which is
    what makes the chunk boundary pure data movement."""
    return paged_pour_blocks(cache, kv[None], [int(block_id)])


def gathered_attention(q, keys, vals, seq_lens, *, scale=None):
    """The sdpa core of the decode tier over ALREADY-GATHERED views:
    q [B, T, N, H]; keys/vals [B, Nkv, S, H] (dequantized); seq_lens [B]
    INCLUDING all T chunk tokens.  The ONE masked-softmax definition —
    paged_chunk_attention feeds it the paged_gather views and the fused
    decode-chain kernel (ops/decode_chain.py) feeds it VMEM-gathered
    pages, so the two paths cannot drift numerically."""
    b, t, n, h = q.shape
    nkv = keys.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(h)
    if n != nkv:
        group = n // nkv
        keys = jnp.repeat(keys, group, axis=1)
        vals = jnp.repeat(vals, group, axis=1)
    logits = jnp.einsum(
        "btnh,bnsh->bnts", q.astype(jnp.float32), keys.astype(jnp.float32)
    ) * jnp.float32(scale)
    span = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)  # key pos
    qpos = (seq_lens[:, None] - t + jnp.arange(t, dtype=jnp.int32)[None, :])
    allowed = span <= qpos[:, None, :, None]
    logits = jnp.where(allowed, logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnts,bnsh->btnh", probs, vals.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_chunk_attention(q, key_cache, value_cache, block_tables, seq_lens,
                          *, scale=None):
    """Multi-token decode attention over the paged cache (speculative
    verify / chunked decode): q [B, T, N, H]; seq_lens [B] INCLUDING all
    T chunk tokens.  Chunk position j sits at global position
    seq_lens - T + j and attends keys <= that position (bottom-right
    causal within the chunk).  Returns [B, T, N, H]."""
    keys = paged_gather(key_cache, block_tables)  # [B, Nkv, S, H]
    vals = paged_gather(value_cache, block_tables)
    return gathered_attention(q, keys, vals, seq_lens, scale=scale)
