"""Paged-KV (block) attention for serving.

Reference: the block attention serving tier —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and
python/paddle/incubate/nn/functional/block_multihead_attention.py: the KV
cache is a pool of fixed-size blocks; each sequence owns a block table
mapping its logical positions onto pool blocks, so cache memory is allocated
per-16-token page instead of per-max-seq-len (vLLM-style paging).

TPU-native design: the pool is ONE [num_blocks, Nkv, block_size, H] array per
K and V; block writes are scatter-at-index updates and decode attention
gathers each sequence's pages with jnp.take on the block table.  Both lower
to XLA dynamic-scatter/gather which on TPU are HBM-bandwidth-bound copies —
the same roofline the hand-written CUDA kernel targets — and the whole
decode step (gather + QK^T + softmax + PV) fuses into one executable.
Everything is shape-static: max_blocks_per_seq bounds the gather and a
length mask handles raggedness, so the step jits once and is reused for the
whole decode.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = [
    "alloc_paged_cache",
    "paged_write",
    "paged_decode_attention",
]


def rope_rotate_by_position(t, cos, sin, positions):
    """Interleaved-pair rotation of per-token heads by gathered positions.

    t: [B, N, H]; cos/sin: [max_len, H/2] tables; positions: [B] int32.
    The SINGLE rope implementation for decode paths (model prefill uses the
    same pair convention in models/llama.py apply_rotary_pos_emb) — change
    rope semantics here and there together.
    """
    # the T=1 case of rope_rotate_chunk — ONE implementation of the pair
    # convention (change rope semantics there, not here)
    return rope_rotate_chunk(t[:, None], cos, sin, positions[:, None])[:, 0]


def alloc_paged_cache(num_blocks, num_kv_heads, block_size, head_dim, dtype=jnp.bfloat16):
    """One K and one V pool: [num_blocks, Nkv, block_size, H]."""
    shape = (num_blocks, num_kv_heads, block_size, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_write(cache, new, block_tables, positions):
    """Write one token per sequence into its page.

    cache: [num_blocks, Nkv, bs, H]; new: [B, Nkv, H];
    block_tables: [B, max_blocks] int32; positions: [B] int32 (token index
    within the sequence).  Returns the updated cache.
    """
    # the T=1 case of paged_write_chunk — one scatter implementation
    return paged_write_chunk(cache, new[:, None], block_tables,
                             positions[:, None])


def paged_gather(cache, block_tables):
    """Materialize each sequence's logical cache view.

    cache: [num_blocks, Nkv, bs, H]; block_tables: [B, max_blocks] ->
    [B, Nkv, max_blocks*bs, H].
    """
    pages = jnp.take(cache, block_tables, axis=0)  # [B, max_blocks, Nkv, bs, H]
    b, mb, nkv, bs, h = pages.shape
    return jnp.moveaxis(pages, 2, 1).reshape(b, nkv, mb * bs, h)


def paged_decode_attention(q, key_cache, value_cache, block_tables, seq_lens, *, scale=None):
    """Single-token decode attention over the paged cache.

    q: [B, N, H] (the new token's queries, rope already applied);
    key_cache/value_cache: [num_blocks, Nkv, bs, H]; block_tables:
    [B, max_blocks]; seq_lens: [B] VALID length (including the new token).
    GQA: N may be a multiple of Nkv.  Returns [B, N, H].
    """
    # the T=1 case of paged_chunk_attention — one masked-softmax
    # implementation for the decode tier
    return paged_chunk_attention(q[:, None], key_cache, value_cache,
                                 block_tables, seq_lens, scale=scale)[:, 0]


def rope_rotate_chunk(t, cos, sin, positions):
    """Chunk variant of rope_rotate_by_position: t [B, T, N, H],
    positions [B, T] int32."""
    b, tt, n, h = t.shape
    c = jnp.take(jnp.asarray(cos), positions, axis=0)[:, :, None, :]  # [B,T,1,H/2]
    s = jnp.take(jnp.asarray(sin), positions, axis=0)[:, :, None, :]
    t2 = t.astype(jnp.float32).reshape(b, tt, n, h // 2, 2)
    r1 = t2[..., 0] * c - t2[..., 1] * s
    r2 = t2[..., 1] * c + t2[..., 0] * s
    return jnp.stack([r1, r2], -1).reshape(b, tt, n, h).astype(t.dtype)


def paged_write_chunk(cache, new, block_tables, positions):
    """Write T tokens per sequence into their pages.

    cache: [num_blocks, Nkv, bs, H]; new: [B, T, Nkv, H]; positions:
    [B, T] int32 (token index within each sequence).  The [B, T] scatter
    is one advanced-indexing update — speculative verify writes its whole
    chunk in one shot."""
    bs = cache.shape[2]
    block_idx = jnp.take_along_axis(block_tables, positions // bs, axis=1)  # [B,T]
    slot = positions % bs
    # advanced indexing on dims 0 and 2 with [B, T] index arrays puts the
    # broadcast [B, T] in front: value shape [B, T, Nkv, H] == new
    return cache.at[block_idx, :, slot, :].set(new)


def paged_chunk_attention(q, key_cache, value_cache, block_tables, seq_lens,
                          *, scale=None):
    """Multi-token decode attention over the paged cache (speculative
    verify / chunked decode): q [B, T, N, H]; seq_lens [B] INCLUDING all
    T chunk tokens.  Chunk position j sits at global position
    seq_lens - T + j and attends keys <= that position (bottom-right
    causal within the chunk).  Returns [B, T, N, H]."""
    b, t, n, h = q.shape
    nkv = key_cache.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(h)
    keys = paged_gather(key_cache, block_tables)  # [B, Nkv, S, H]
    vals = paged_gather(value_cache, block_tables)
    if n != nkv:
        group = n // nkv
        keys = jnp.repeat(keys, group, axis=1)
        vals = jnp.repeat(vals, group, axis=1)
    logits = jnp.einsum(
        "btnh,bnsh->bnts", q.astype(jnp.float32), keys.astype(jnp.float32)
    ) * jnp.float32(scale)
    span = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)  # key pos
    qpos = (seq_lens[:, None] - t + jnp.arange(t, dtype=jnp.int32)[None, :])
    allowed = span <= qpos[:, None, :, None]
    logits = jnp.where(allowed, logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnts,bnsh->btnh", probs, vals.astype(jnp.float32))
    return out.astype(q.dtype)
