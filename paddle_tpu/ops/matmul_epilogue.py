"""Fused matmul + bias + activation Pallas kernel (the matmul-epilogue
fusion family).

Reference capability: CINN fusion groups / epilogue fusion
(paddle/cinn/hlir/framework/op_lowering_impl.cc — matmul+bias+act chains),
phi fused kernels like fused_gemm_epilogue.

TPU shape: a blocked MXU matmul accumulating in f32 VMEM scratch; the
epilogue (bias add + gelu/silu/relu) runs on the final K step on the
accumulator while it is still in VMEM — the intermediate [M, N] pre-
activation never round-trips HBM.  Tiles come from the measured autotune
cache (ops/autotune.py, kernel "matmul_epilogue") with VMEM-safe analytic
defaults; shapes the grid cannot tile cleanly fall back to plain XLA
(which fuses simple epilogues well — the kernel exists for the cases it
does not, and for tile control).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops._pl_utils import imap
from jax.experimental.pallas import tpu as pltpu

__all__ = ["matmul_bias_act"]

_ACTS = {
    "none": lambda v: v,
    "relu": lambda v: jnp.maximum(v, 0.0),
    "gelu": lambda v: jax.nn.gelu(v, approximate=False),
    "gelu_tanh": lambda v: jax.nn.gelu(v, approximate=True),
    "silu": lambda v: v * jax.nn.sigmoid(v),
}


def _kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, act, k_steps, has_bias):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        x_ref[:], w_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        r = acc_ref[:]
        if has_bias:
            r = r + b_ref[:].astype(jnp.float32)
        o_ref[:] = _ACTS[act](r).astype(o_ref.dtype)


def _pick_tiles(M, K, N, dtype):
    from paddle_tpu.ops import autotune as _at

    tuned = _at.lookup("matmul_epilogue", {
        "m": M, "k": K, "n": N, "dtype": jnp.dtype(dtype).name})
    if tuned:
        bm, bk, bn = int(tuned["bm"]), int(tuned["bk"]), int(tuned["bn"])
        if M % bm == 0 and K % bk == 0 and N % bn == 0:
            return bm, bk, bn

    def best(total, cands):
        for c in cands:
            if total % c == 0:
                return c
        return None

    # MXU-friendly defaults; the f32 accumulator block (bm x bn) plus the
    # double-buffered inputs must sit in VMEM: 256x256x4B acc = 256KB.
    bm = best(M, (256, 128, 64, 32, 16, 8))
    bn = best(N, (256, 128))
    bk = best(K, (512, 256, 128))
    if bm is None or bn is None or bk is None:
        return None
    return bm, bk, bn


def _fused_2d(x2d, w, bias, act, tiles=None):
    M, K = x2d.shape
    N = w.shape[1]
    tiles = tiles or _pick_tiles(M, K, N, x2d.dtype)
    if tiles is None:
        return None
    bm, bk, bn = tiles
    has_bias = bias is not None
    b = bias if has_bias else jnp.zeros((N,), x2d.dtype)
    grid = (M // bm, N // bn, K // bk)
    return pl.pallas_call(
        functools.partial(_kernel, act=act, k_steps=grid[2], has_bias=has_bias),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), imap(lambda i, j, k: (i, k))),
            pl.BlockSpec((bk, bn), imap(lambda i, j, k: (k, j))),
            pl.BlockSpec((bn,), imap(lambda i, j, k: (j,))),
        ],
        out_specs=pl.BlockSpec((bm, bn), imap(lambda i, j, k: (i, j))),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=jax.default_backend() != "tpu",
    )(x2d, w, b)


def _replay(x2d, w, bias, act):
    """The epilogue math in plain XLA — the fallback path AND the backward
    replay (one definition of the semantics)."""
    r = jnp.matmul(x2d, w)
    if bias is not None:
        r = r + bias
    return _ACTS[act](r.astype(jnp.float32)).astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _mm_epilogue(x2d, w, bias, act):
    out = _fused_2d(x2d, w, bias, act)
    if out is None:
        out = _replay(x2d, w, bias, act)
    return out


def _mm_fwd(x2d, w, bias, act):
    return _mm_epilogue(x2d, w, bias, act), (x2d, w, bias)


def _mm_bwd(act, res, g):
    x2d, w, bias = res
    if bias is None:
        _, vjp = jax.vjp(lambda xa, wa: _replay(xa, wa, None, act), x2d, w)
        dx, dw = vjp(g)
        return dx, dw, None
    _, vjp = jax.vjp(lambda xa, wa, ba: _replay(xa, wa, ba, act), x2d, w, bias)
    return vjp(g)


_mm_epilogue.defvjp(_mm_fwd, _mm_bwd)


def matmul_bias_act(x, weight, bias=None, activation="none"):
    """act(x @ weight + bias) with the epilogue fused into the matmul.

    x: [..., K]; weight: [K, N]; bias: [N] or None;
    activation: none | relu | gelu | gelu_tanh | silu.
    """
    if activation not in _ACTS:
        raise ValueError(f"unknown activation {activation!r}; have {sorted(_ACTS)}")
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    out = _mm_epilogue(x2d, weight, bias, activation)
    return out.reshape(shape[:-1] + (weight.shape[1],))
