"""paddle_tpu.ops — Pallas TPU kernel library.

This package is the TPU-native analog of the reference's fused CUDA kernels
(paddle/phi/kernels/fusion/gpu/: fused_rope_kernel.cu, fused_layernorm_kernel.cu,
fused_rms_norm .. and paddle/phi/kernels/gpu/flash_attn_kernel.cu).  Each op
ships two implementations:

- a Pallas TPU kernel (MXU/VPU-tiled, VMEM-resident, custom VJP), used when
  running on TPU hardware;
- a pure jax/jnp reference with identical semantics, used on CPU test meshes
  and as the numerics oracle (Pallas kernels are additionally unit-tested in
  interpreter mode against it).

Dispatch is `use_pallas()`: TPU backend by default, overridable via the flag
`FLAGS_use_pallas` (paddle_tpu.set_flags) for A/B benchmarking.

This library also plays the role of the reference's KPS tier
(paddle/phi/kernels/primitive/, Backend::KPS — the "write once, run
per-backend" kernel-authoring primitives): Pallas IS the portable
kernel-authoring layer on the XLA stack (same kernel source lowers to TPU
Mosaic or interpret-mode CPU; GPU Triton lowering exists upstream), so no
separate primitive API is reproduced.
"""

from __future__ import annotations

import jax

from paddle_tpu._core import flags as _flags

_flags.define_flag("FLAGS_use_pallas", "auto", "auto|true|false — Pallas kernel dispatch")
_flags.define_flag("FLAGS_flash_block_q", 0,
                   "flash attention q-block rows override; 0 = consult the "
                   "autotune cache, then the 128 default")
_flags.define_flag("FLAGS_flash_block_k", 0,
                   "flash attention k-block rows override; 0 = consult the "
                   "autotune cache, then the 128 default")
_flags.define_flag("FLAGS_use_autotune_cache", True,
                   "consult ops/tuned/<device_kind>.json for Pallas tile configs")
_flags.define_flag("FLAGS_autotune_cache_dir", "",
                   "where `python -m paddle_tpu.ops.autotune` saves tuned tiles "
                   "(empty = the package's ops/tuned/ seed directory)")


def use_pallas() -> bool:
    v = str(_flags.flag("FLAGS_use_pallas")).lower()
    if v in ("true", "1"):
        return True
    if v in ("false", "0"):
        return False
    return jax.default_backend() == "tpu"


from .flash_attention import flash_attention, flash_attention_reference  # noqa: E402,F401
from .fused_norm import fused_rms_norm, fused_layer_norm  # noqa: E402,F401
from .fused_rope import fused_rotary_position_embedding  # noqa: E402,F401
from .swiglu import swiglu  # noqa: E402,F401
from .matmul_epilogue import matmul_bias_act  # noqa: E402,F401
from .ring_attention import ring_attention, ulysses_attention  # noqa: E402,F401
