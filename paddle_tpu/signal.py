"""paddle.signal equivalent (reference: python/paddle/signal.py — frame,
overlap_add, stft, istft over phi frame/overlap_add kernels + fft).

TPU-first: frame is a strided gather (one XLA gather, no data copy loops),
overlap_add is a segment-sum scatter, stft/istft compose them with the fft
module so the whole pipeline stays fusible under jit."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames (reference signal.py:12).

    axis=-1: [..., seq] → [..., frame_length, num_frames]
    axis=0:  [seq, ...] → [num_frames, frame_length, ...]
    """
    xv = _v(x)
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    seq = xv.shape[axis]
    if frame_length > seq:
        raise ValueError(f"frame_length ({frame_length}) > sequence length ({seq})")
    n_frames = 1 + (seq - frame_length) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    offsets = jnp.arange(frame_length)
    gather_idx = starts[:, None] + offsets[None, :]  # [n_frames, frame_length]
    if axis == 0:  # checked first: for 1-D input axis 0 and -1 coincide but
        # paddle's output layout differs by the axis argument
        out = jnp.take(xv, gather_idx, axis=0)  # [n_frames, frame_length, ...]
        return Tensor(out)
    if axis in (-1, xv.ndim - 1):
        out = jnp.take(xv, gather_idx, axis=-1)  # [..., n_frames, frame_length]
        return Tensor(jnp.swapaxes(out, -1, -2))  # [..., frame_length, n_frames]
    raise ValueError("axis must be 0 or -1")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference signal.py:110).

    axis=-1: [..., frame_length, num_frames] → [..., seq]
    axis=0:  [num_frames, frame_length, ...] → [seq, ...]
    """
    xv = _v(x)
    if axis == 0:
        n_frames, frame_length = xv.shape[0], xv.shape[1]
        seq = (n_frames - 1) * hop_length + frame_length
        pos = (jnp.arange(n_frames) * hop_length)[:, None] + jnp.arange(frame_length)[None, :]
        flat_pos = pos.reshape(-1)
        flat = xv.reshape((n_frames * frame_length,) + xv.shape[2:])
        out = jnp.zeros((seq,) + xv.shape[2:], xv.dtype).at[flat_pos].add(flat)
        return Tensor(out)
    if axis in (-1, xv.ndim - 1):
        frame_length, n_frames = xv.shape[-2], xv.shape[-1]
        frames = jnp.swapaxes(xv, -1, -2)  # [..., n_frames, frame_length]
        lead = frames.shape[:-2]
        seq = (n_frames - 1) * hop_length + frame_length
        pos = (jnp.arange(n_frames) * hop_length)[:, None] + jnp.arange(frame_length)[None, :]
        flat_pos = pos.reshape(-1)
        flat = frames.reshape(lead + (-1,))
        out = jnp.zeros(lead + (seq,), xv.dtype).at[..., flat_pos].add(flat)
        return Tensor(out)
    raise ValueError("axis must be 0 or -1")


def stft(
    x,
    n_fft,
    hop_length=None,
    win_length=None,
    window=None,
    center=True,
    pad_mode="reflect",
    normalized=False,
    onesided=True,
    name=None,
):
    """Short-time Fourier transform (reference signal.py:191).

    x: [..., seq] real or complex → [..., n_fft(/2+1), num_frames] complex.
    """
    xv = _v(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        w = _v(window)
    else:
        w = jnp.ones(win_length, jnp.real(xv).dtype)
    if win_length < n_fft:  # centre-pad window to n_fft
        pad_l = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad_l, n_fft - win_length - pad_l))
    if center:
        pad = n_fft // 2
        pad_widths = [(0, 0)] * (xv.ndim - 1) + [(pad, pad)]
        xv = jnp.pad(xv, pad_widths, mode=pad_mode)
    frames = _v(frame(Tensor(xv), n_fft, hop_length, axis=-1))  # [..., n_fft, n_frames]
    frames = frames * w[:, None]
    if jnp.iscomplexobj(xv):
        if onesided:
            raise ValueError("stft of a complex signal requires onesided=False")
        spec = jnp.fft.fft(frames, n=n_fft, axis=-2)
    elif not onesided:
        spec = jnp.fft.fft(frames, n=n_fft, axis=-2)
    else:
        spec = jnp.fft.rfft(frames, n=n_fft, axis=-2)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.real(spec).dtype))
    return Tensor(spec)


def istft(
    x,
    n_fft,
    hop_length=None,
    win_length=None,
    window=None,
    center=True,
    normalized=False,
    onesided=True,
    length=None,
    return_complex=False,
    name=None,
):
    """Inverse STFT with window-envelope normalization (reference
    signal.py:336)."""
    spec = _v(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        w = _v(window)
    else:
        w = jnp.ones(win_length, jnp.float32)
    if win_length < n_fft:
        pad_l = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad_l, n_fft - win_length - pad_l))
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    if onesided and return_complex:
        raise ValueError("istft: onesided=True cannot produce complex output")
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)  # [..., n_fft, n_frames]
    else:
        frames = jnp.fft.ifft(spec, n=n_fft, axis=-2)
        if not return_complex:
            frames = frames.real
    frames = frames * w[:, None]
    out = _v(overlap_add(Tensor(frames), hop_length, axis=-1))
    # normalize by the summed squared window envelope
    wsq = jnp.broadcast_to((w**2)[:, None], (n_fft, frames.shape[-1]))
    envelope = _v(overlap_add(Tensor(wsq), hop_length, axis=-1))
    out = out / jnp.where(envelope > 1e-11, envelope, 1.0)
    if center:
        pad = n_fft // 2
        out = out[..., pad:-pad] if pad else out
    if length is not None:
        out = out[..., :length]
    return Tensor(out)
