"""paddle.vision equivalent (reference: python/paddle/vision/)."""

from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401

# image IO backend selector (reference: python/paddle/vision/image.py)
_image_backend = "pil"


def set_image_backend(backend):
    """reference: paddle.vision.set_image_backend — 'pil' | 'cv2' |
    'tensor'.  cv2 is accepted only if importable."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    if backend == "cv2":
        try:
            import cv2  # noqa: F401
        except ImportError as e:
            raise ImportError("cv2 backend requested but opencv is not installed") from e
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """reference: paddle.vision.image_load — read an image file with the
    selected backend; 'tensor' returns a CHW uint8 Tensor."""
    b = backend or _image_backend
    if b == "cv2":
        import cv2

        return cv2.imread(path)
    from PIL import Image

    img = Image.open(path)
    if b == "pil":
        return img
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu._core.tensor import Tensor

    arr = np.asarray(img.convert("RGB")).transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
