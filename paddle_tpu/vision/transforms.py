"""Vision transforms (reference: python/paddle/vision/transforms/) — host-side
numpy preprocessing feeding the DataLoader."""

from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad", "RandomResizedCrop",
    "ColorJitter", "Grayscale", "BrightnessTransform", "ContrastTransform",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop", "pad",
]


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def to_tensor(img, data_format="CHW"):
    arr = _to_np(img).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = [mean] * 3 if isinstance(mean, numbers.Number) else mean
        self.std = [std] * 3 if isinstance(std, numbers.Number) else std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def _resize_np(arr, size):
    """Nearest+linear resize via jax.image on host arrays (HWC)."""
    import jax.image

    h, w = (size, size) if isinstance(size, int) else size
    out = jax.image.resize(arr, (h, w) + arr.shape[2:], method="linear")
    return np.asarray(out)


def resize(img, size, interpolation="bilinear"):
    arr = _to_np(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    return _resize_np(arr, size)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    arr = _to_np(img)
    return arr[top : top + height, left : left + width]


def center_crop(img, output_size):
    arr = _to_np(img)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    h, w = arr.shape[:2]
    top = (h - oh) // 2
    left = (w - ow) // 2
    return crop(arr, top, left, oh, ow)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _to_np(img)
        if self.padding:
            arr = np.pad(arr, [(self.padding, self.padding), (self.padding, self.padding)] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[:2]
        oh, ow = self.size
        top = pyrandom.randint(0, max(h - oh, 0))
        left = pyrandom.randint(0, max(w - ow, 0))
        return arr[top : top + oh, left : left + ow]


def hflip(img):
    return _to_np(img)[:, ::-1].copy()


def vflip(img):
    return _to_np(img)[::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _to_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _to_np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_np(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    widths = [(padding[1], padding[3]), (padding[0], padding[2])] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(arr, widths, mode=mode, constant_values=fill)
    return np.pad(arr, widths, mode=mode)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3), interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            ar = pyrandom.uniform(*self.ratio)
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return _resize_np(arr[top : top + ch, left : left + cw], self.size)
        return _resize_np(center_crop(arr, min(h, w)), self.size)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        factor = 1 + pyrandom.uniform(-self.value, self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1.5 else 1.0)


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        factor = 1 + pyrandom.uniform(-self.value, self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * factor + mean, 0, 255 if arr.max() > 1.5 else 1.0)


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
        return np.stack([gray] * self.n, axis=-1)


# ---------------------------------------------------------------- functional
# (reference: python/paddle/vision/transforms/functional.py; HWC numpy arrays)

def _value_range(img):
    """255 for integer images, else the 0-1 float convention (a dark uint8
    image must not be misread as float by a max-value heuristic)."""
    raw = np.asarray(img._value) if isinstance(img, Tensor) else np.asarray(img)
    if np.issubdtype(raw.dtype, np.integer):
        return 255.0
    return 255.0 if raw.max() > 1.5 else 1.0


def adjust_brightness(img, brightness_factor):
    hi = _value_range(img)
    arr = _to_np(img).astype(np.float32)
    return np.clip(arr * float(brightness_factor), 0, hi)


def adjust_contrast(img, contrast_factor):
    hi = _value_range(img)
    arr = _to_np(img).astype(np.float32)
    gray_mean = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114).mean() if arr.ndim == 3 and arr.shape[-1] == 3 else arr.mean()
    return np.clip((arr - gray_mean) * float(contrast_factor) + gray_mean, 0, hi)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) through HSV space."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    hi = _value_range(img)
    arr = _to_np(img).astype(np.float32)
    x = arr / hi
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = np.max(x, axis=-1)
    minc = np.min(x, axis=-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.clip(maxc, 1e-8, None), 0.0)
    dz = np.clip(delta, 1e-8, None)
    h = np.where(
        maxc == r, (g - b) / dz % 6.0,
        np.where(maxc == g, (b - r) / dz + 2.0, (r - g) / dz + 4.0),
    ) / 6.0
    h = np.where(delta == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    # HSV -> RGB
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    rgb = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], axis=-1)
    return np.clip(rgb * hi, 0, hi)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase region [i:i+h, j:j+w] with value v (reference functional.erase).
    Accepts HWC numpy/PIL or CHW Tensor."""
    if isinstance(img, Tensor):
        import jax.numpy as jnp

        val = jnp.asarray(v, img._value.dtype)
        patch = jnp.broadcast_to(val, (img._value.shape[0], h, w))
        new = img._value.at[:, i : i + h, j : j + w].set(patch)
        return Tensor(new)
    arr = _to_np(img).copy()
    arr[i : i + h, j : j + w] = v
    return arr


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    # RSS (rotate-shear-scale) as in torchvision/paddle functional
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0]], np.float64) * scale
    # T(center+translate) @ RSS @ T(-center)
    m[0, 2] = cx + tx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = cy + ty - m[1, 0] * cx - m[1, 1] * cy
    return m


def _warp_affine(arr, m_inv, out_hw, fill=0.0):
    H, W = out_hw
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    src_x = m_inv[0, 0] * xs + m_inv[0, 1] * ys + m_inv[0, 2]
    src_y = m_inv[1, 0] * xs + m_inv[1, 1] * ys + m_inv[1, 2]
    x0 = np.round(src_x).astype(np.int64)
    y0 = np.round(src_y).astype(np.int64)
    inb = (x0 >= 0) & (x0 < arr.shape[1]) & (y0 >= 0) & (y0 < arr.shape[0])
    out = np.full((H, W) + arr.shape[2:], fill, arr.dtype)
    out[inb] = arr[y0[inb], x0[inb]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest", fill=0, center=None):
    """Affine-transform an HWC image (reference functional.affine)."""
    arr = _to_np(img)
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) / 2.0, (H - 1) / 2.0)
    shear = shear if isinstance(shear, (list, tuple)) else (shear, 0.0)
    m = _affine_matrix(angle, translate, scale, shear, center)
    m3 = np.vstack([m, [0, 0, 1]])
    m_inv = np.linalg.inv(m3)[:2]
    return _warp_affine(arr, m_inv, (H, W), fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    """Rotate an HWC image counter-clockwise (reference functional.rotate)."""
    arr = _to_np(img)
    H, W = arr.shape[:2]
    if expand:
        rad = np.deg2rad(angle)
        nW = int(np.ceil(abs(W * np.cos(rad)) + abs(H * np.sin(rad))))
        nH = int(np.ceil(abs(W * np.sin(rad)) + abs(H * np.cos(rad))))
    else:
        nW, nH = W, H
    if center is None:
        center = ((W - 1) / 2.0, (H - 1) / 2.0)
    m = _affine_matrix(-angle, (0, 0), 1.0, (0.0, 0.0), center)
    m[0, 2] += (nW - W) / 2.0
    m[1, 2] += (nH - H) / 2.0
    m3 = np.vstack([m, [0, 0, 1]])
    m_inv = np.linalg.inv(m3)[:2]
    return _warp_affine(arr, m_inv, (nH, nW), fill)


def _perspective_coeffs(startpoints, endpoints):
    # solve the 8-dof homography mapping endpoints -> startpoints
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.extend([sx, sy])
    coeffs = np.linalg.solve(np.asarray(a, np.float64), np.asarray(b, np.float64))
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Perspective-warp an HWC image (reference functional.perspective)."""
    arr = _to_np(img)
    H, W = arr.shape[:2]
    c = _perspective_coeffs(startpoints, endpoints)
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    denom = c[6] * xs + c[7] * ys + 1.0
    src_x = (c[0] * xs + c[1] * ys + c[2]) / denom
    src_y = (c[3] * xs + c[4] * ys + c[5]) / denom
    x0 = np.round(src_x).astype(np.int64)
    y0 = np.round(src_y).astype(np.int64)
    inb = (x0 >= 0) & (x0 < W) & (y0 >= 0) & (y0 < H)
    out = np.full_like(arr, fill)
    out[inb] = arr[y0[inb], x0[inb]]
    return out


# ------------------------------------------------------------------ classes
class BaseTransform:
    """Transform base with keys plumbing (reference:
    python/paddle/vision/transforms/transforms.py BaseTransform)."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            # entries beyond len(keys) pass through untouched (reference
            # BaseTransform contract — labels must survive the pipeline)
            out = [
                self._apply_image(v) if k == "image" else v
                for k, v in zip(self.keys, inputs)
            ]
            out.extend(inputs[len(self.keys):])
            return tuple(out)
        return self._apply_image(inputs)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        hi = _value_range(img)
        arr = _to_np(img).astype(np.float32)
        factor = 1 + pyrandom.uniform(-self.value, self.value)
        gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
        return np.clip((arr - gray[..., None]) * factor + gray[..., None], 0, hi)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else tuple(degrees)
        self.expand, self.center, self.fill = expand, center, fill
        self.interpolation = interpolation

    def _apply_image(self, img):
        angle = pyrandom.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand, self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None, interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else tuple(degrees)
        if shear is not None and np.isscalar(shear):
            shear = (shear,)
        self.translate, self.scale_rng, self.shear_rng = translate, scale, shear
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def _apply_image(self, img):
        arr = _to_np(img)
        H, W = arr.shape[:2]
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = pyrandom.uniform(-self.translate[0], self.translate[0]) * W
            ty = pyrandom.uniform(-self.translate[1], self.translate[1]) * H
        sc = pyrandom.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (pyrandom.uniform(-self.shear_rng[0], self.shear_rng[0]) if self.shear_rng else 0.0, 0.0)
        return affine(arr, angle, (tx, ty), sc, sh, self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.d = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        if pyrandom.random() >= self.prob:
            return _to_np(img)
        arr = _to_np(img)
        H, W = arr.shape[:2]
        dx, dy = int(self.d * W / 2), int(self.d * H / 2)
        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [
            (pyrandom.randint(0, dx), pyrandom.randint(0, dy)),
            (W - 1 - pyrandom.randint(0, dx), pyrandom.randint(0, dy)),
            (W - 1 - pyrandom.randint(0, dx), H - 1 - pyrandom.randint(0, dy)),
            (pyrandom.randint(0, dx), H - 1 - pyrandom.randint(0, dy)),
        ]
        return perspective(arr, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """reference transforms.RandomErasing (Zhong et al. 2020)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        arr = _to_np(img)
        if pyrandom.random() >= self.prob:
            return arr
        chw = isinstance(img, Tensor)
        H, W = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = H * W
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            ar = pyrandom.uniform(*self.ratio)
            h = int(round((target * ar) ** 0.5))
            w = int(round((target / ar) ** 0.5))
            if h < H and w < W:
                i = pyrandom.randint(0, H - h)
                j = pyrandom.randint(0, W - w)
                return erase(img, i, j, h, w, self.value)
        return arr


__all__ += [
    "BaseTransform", "HueTransform", "SaturationTransform", "RandomAffine",
    "RandomErasing", "RandomPerspective", "RandomRotation",
    "adjust_brightness", "adjust_contrast", "adjust_hue", "affine", "erase",
    "perspective", "rotate", "to_grayscale",
]
