"""Vision transforms (reference: python/paddle/vision/transforms/) — host-side
numpy preprocessing feeding the DataLoader."""

from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad", "RandomResizedCrop",
    "ColorJitter", "Grayscale", "BrightnessTransform", "ContrastTransform",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop", "pad",
]


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def to_tensor(img, data_format="CHW"):
    arr = _to_np(img).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = [mean] * 3 if isinstance(mean, numbers.Number) else mean
        self.std = [std] * 3 if isinstance(std, numbers.Number) else std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def _resize_np(arr, size):
    """Nearest+linear resize via jax.image on host arrays (HWC)."""
    import jax.image

    h, w = (size, size) if isinstance(size, int) else size
    out = jax.image.resize(arr, (h, w) + arr.shape[2:], method="linear")
    return np.asarray(out)


def resize(img, size, interpolation="bilinear"):
    arr = _to_np(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    return _resize_np(arr, size)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    arr = _to_np(img)
    return arr[top : top + height, left : left + width]


def center_crop(img, output_size):
    arr = _to_np(img)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    h, w = arr.shape[:2]
    top = (h - oh) // 2
    left = (w - ow) // 2
    return crop(arr, top, left, oh, ow)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _to_np(img)
        if self.padding:
            arr = np.pad(arr, [(self.padding, self.padding), (self.padding, self.padding)] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[:2]
        oh, ow = self.size
        top = pyrandom.randint(0, max(h - oh, 0))
        left = pyrandom.randint(0, max(w - ow, 0))
        return arr[top : top + oh, left : left + ow]


def hflip(img):
    return _to_np(img)[:, ::-1].copy()


def vflip(img):
    return _to_np(img)[::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _to_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _to_np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_np(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    widths = [(padding[1], padding[3]), (padding[0], padding[2])] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(arr, widths, mode=mode, constant_values=fill)
    return np.pad(arr, widths, mode=mode)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3), interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            ar = pyrandom.uniform(*self.ratio)
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return _resize_np(arr[top : top + ch, left : left + cw], self.size)
        return _resize_np(center_crop(arr, min(h, w)), self.size)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        factor = 1 + pyrandom.uniform(-self.value, self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1.5 else 1.0)


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        factor = 1 + pyrandom.uniform(-self.value, self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * factor + mean, 0, 255 if arr.max() > 1.5 else 1.0)


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def __call__(self, img):
        arr = _to_np(img).astype(np.float32)
        gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
        return np.stack([gray] * self.n, axis=-1)
