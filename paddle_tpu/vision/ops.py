"""Vision ops (reference: python/paddle/vision/ops.py — roi_align,
deform_conv2d, nms, box utilities)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.tensor._ops_common import Tensor, apply, ensure_tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "deform_conv2d", "DeformConv2D", "distribute_fpn_proposals", "generate_proposals", "PSRoIPool", "RoIAlign", "RoIPool", "yolo_box", "prior_box", "matrix_nms", "psroi_pool", "yolo_loss", "read_file", "decode_jpeg"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Non-maximum suppression — data-dependent output, so eager/host-side
    (the reference's CUDA NMS is also a sync point)."""
    b = np.asarray(ensure_tensor(boxes)._value)
    s = np.asarray(ensure_tensor(scores)._value) if scores is not None else np.arange(len(b))[::-1].astype(np.float32)
    cat = np.asarray(ensure_tensor(category_idxs)._value) if category_idxs is not None else np.zeros(len(b), np.int64)
    keep_all = []
    for c in np.unique(cat):
        idx = np.where(cat == c)[0]
        order = idx[np.argsort(-s[idx])]
        keep = []
        while len(order):
            i = order[0]
            keep.append(i)
            if len(order) == 1:
                break
            rest = order[1:]
            xx1 = np.maximum(b[i, 0], b[rest, 0])
            yy1 = np.maximum(b[i, 1], b[rest, 1])
            xx2 = np.minimum(b[i, 2], b[rest, 2])
            yy2 = np.minimum(b[i, 3], b[rest, 3])
            inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
            area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
            area_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
            iou = inter / (area_i + area_r - inter + 1e-10)
            order = rest[iou <= iou_threshold]
        keep_all.extend(keep)
    keep_all = sorted(keep_all, key=lambda i: -s[i])
    if top_k is not None:
        keep_all = keep_all[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep_all, np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid gather — XLA-friendly static shapes."""
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size

    def _roi(feat, bxs):
        n_rois = bxs.shape[0]
        offset = 0.5 if aligned else 0.0
        ratio = sampling_ratio if sampling_ratio > 0 else 2

        def one_roi(box):
            x1, y1, x2, y2 = box[0] * spatial_scale - offset, box[1] * spatial_scale - offset, box[2] * spatial_scale - offset, box[3] * spatial_scale - offset
            rw = jnp.maximum(x2 - x1, 1e-6)
            rh = jnp.maximum(y2 - y1, 1e-6)
            bin_w = rw / ow
            bin_h = rh / oh
            ys = y1 + (jnp.arange(oh)[:, None, None, None] + (jnp.arange(ratio)[None, :, None, None] + 0.5) / ratio) * bin_h
            xs = x1 + (jnp.arange(ow)[None, None, :, None] + (jnp.arange(ratio)[None, None, None, :] + 0.5) / ratio) * bin_w
            ys = jnp.broadcast_to(ys, (oh, ratio, ow, ratio)).reshape(-1)
            xs = jnp.broadcast_to(xs, (oh, ratio, ow, ratio)).reshape(-1)
            H, W = feat.shape[2], feat.shape[3]
            y0 = jnp.clip(jnp.floor(ys), 0, H - 1).astype(jnp.int32)
            x0 = jnp.clip(jnp.floor(xs), 0, W - 1).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            ly = jnp.clip(ys - y0, 0, 1)
            lx = jnp.clip(xs - x0, 0, 1)
            f = feat[0]  # assumes rois refer to batch 0 slice per-roi via boxes_num; simple path
            v = (
                f[:, y0, x0] * (1 - ly) * (1 - lx)
                + f[:, y1i, x0] * ly * (1 - lx)
                + f[:, y0, x1i] * (1 - ly) * lx
                + f[:, y1i, x1i] * ly * lx
            )
            v = v.reshape(f.shape[0], oh, ratio, ow, ratio).mean(axis=(2, 4))
            return v

        return jax.vmap(one_roi)(bxs)

    return apply("roi_align", _roi, x, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size

    def _rp(feat, bxs):
        H, W = feat.shape[2], feat.shape[3]

        def one(box):
            x1 = jnp.floor(box[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.floor(box[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.ceil(box[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.ceil(box[3] * spatial_scale).astype(jnp.int32)
            # static grid sampling: sample a dense grid then maxpool regions
            ys = jnp.linspace(y1.astype(jnp.float32), jnp.maximum(y2 - 1, y1).astype(jnp.float32), oh * 2)
            xs = jnp.linspace(x1.astype(jnp.float32), jnp.maximum(x2 - 1, x1).astype(jnp.float32), ow * 2)
            yi = jnp.clip(jnp.round(ys), 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(jnp.round(xs), 0, W - 1).astype(jnp.int32)
            g = feat[0][:, yi][:, :, xi]
            return g.reshape(feat.shape[1], oh, 2, ow, 2).max(axis=(2, 4))

        return jax.vmap(one)(bxs)

    return apply("roi_pool", _rp, x, boxes)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size", box_normalized=True, axis=0, name=None):
    prior_box, target_box = ensure_tensor(prior_box), ensure_tensor(target_box)
    var = ensure_tensor(prior_box_var) if prior_box_var is not None and not isinstance(prior_box_var, list) else None

    def _coder(pb, tb, *rest):
        v = rest[0] if rest else (jnp.asarray(prior_box_var, tb.dtype) if isinstance(prior_box_var, list) else jnp.ones((4,), tb.dtype))
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx - pcx) / pw / v[..., 0]
            dy = (tcy - pcy) / ph / v[..., 1]
            dw = jnp.log(tw / pw) / v[..., 2]
            dh = jnp.log(th / ph) / v[..., 3]
            return jnp.stack([dx, dy, dw, dh], axis=-1)
        # decode
        d = tb
        cx = d[..., 0] * v[..., 0] * pw + pcx
        cy = d[..., 1] * v[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2] * v[..., 2]) * pw
        h = jnp.exp(d[..., 3] * v[..., 3]) * ph
        return jnp.stack([cx - w * 0.5, cy - h * 0.5, cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)

    extra = [var] if var is not None else []
    return apply("box_coder", _coder, prior_box, target_box, *extra)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1, deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 via explicit bilinear sampling (reference CUDA
    kernel paddle/phi/kernels/gpu/deformable_conv_kernel.cu) — gather-based,
    static shapes, vmap over batch."""
    x, offset, weight = ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def _dcn(feat, off, w, *rest):
        it = iter(rest)
        b_arr = next(it) if bias is not None else None
        m_arr = next(it) if mask is not None else None
        N, C, H, W = feat.shape
        Cout, Cin_g, kh, kw = w.shape
        out_h = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        out_w = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        fpad = jnp.pad(feat, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        Hp, Wp = H + 2 * p[0], W + 2 * p[1]

        base_y = jnp.arange(out_h) * s[0]
        base_x = jnp.arange(out_w) * s[1]
        ky = jnp.arange(kh) * d[0]
        kx = jnp.arange(kw) * d[1]
        # grid positions [kh,kw,out_h,out_w]
        gy = base_y[None, None, :, None] + ky[:, None, None, None]
        gx = base_x[None, None, None, :] + kx[None, :, None, None]

        def per_image(fi, oi, mi):
            # oi: [2*dg*kh*kw, out_h, out_w]
            oi = oi.reshape(deformable_groups, 2, kh, kw, out_h, out_w)

            def per_dg(fg, og, mg):
                yy = gy + og[0]
                xx = gx + og[1]
                y0 = jnp.floor(yy)
                x0 = jnp.floor(xx)
                ly = yy - y0
                lx = xx - x0
                y0c = jnp.clip(y0.astype(jnp.int32), 0, Hp - 1)
                x0c = jnp.clip(x0.astype(jnp.int32), 0, Wp - 1)
                y1c = jnp.clip(y0c + 1, 0, Hp - 1)
                x1c = jnp.clip(x0c + 1, 0, Wp - 1)
                valid = ((yy >= 0) & (yy <= Hp - 1) & (xx >= 0) & (xx <= Wp - 1)).astype(fg.dtype)
                v = (
                    fg[:, y0c, x0c] * (1 - ly) * (1 - lx)
                    + fg[:, y1c, x0c] * ly * (1 - lx)
                    + fg[:, y0c, x1c] * (1 - ly) * lx
                    + fg[:, y1c, x1c] * ly * lx
                ) * valid
                if mg is not None:
                    v = v * mg
                return v  # [C_dg, kh, kw, out_h, out_w]

            cg = C // deformable_groups
            cols = []
            for g in range(deformable_groups):
                mg = mi.reshape(deformable_groups, kh, kw, out_h, out_w)[g] if mi is not None else None
                cols.append(per_dg(fi[g * cg : (g + 1) * cg], oi[g], mg))
            col = jnp.concatenate(cols, axis=0)  # [C, kh, kw, oh, ow]
            # grouped conv as matmul
            og_list = []
            cpg = C // groups
            opg = Cout // groups
            for g in range(groups):
                colg = col[g * cpg : (g + 1) * cpg].reshape(cpg * kh * kw, out_h * out_w)
                wg = w[g * opg : (g + 1) * opg].reshape(opg, cpg * kh * kw)
                og_list.append(wg @ colg)
            out = jnp.concatenate(og_list, axis=0).reshape(Cout, out_h, out_w)
            return out

        mi_arr = m_arr if m_arr is not None else [None] * N
        outs = []
        for i in range(N):
            outs.append(per_image(fpad[i], off[i], m_arr[i] if m_arr is not None else None))
        out = jnp.stack(outs)
        if b_arr is not None:
            out = out + b_arr.reshape(1, -1, 1, 1)
        return out

    extra = [ensure_tensor(t) for t in (bias, mask) if t is not None]
    return apply("deform_conv2d", _dcn, x, offset, weight, *extra)


class DeformConv2D:
    """Layer wrapper for deform_conv2d (reference paddle.vision.ops.DeformConv2D)."""

    def __new__(cls, *args, **kwargs):
        from paddle_tpu.nn import Layer
        from paddle_tpu.nn import initializer as I

        class _DeformConv2D(Layer):
            def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, deformable_groups=1, groups=1, weight_attr=None, bias_attr=None):
                super().__init__()
                ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
                self._args = (stride, padding, dilation, deformable_groups, groups)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, *ks], attr=weight_attr, default_initializer=I.XavierNormal()
                )
                self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True) if bias_attr is not False else None

            def forward(self, x, offset, mask=None):
                s, p, d, dg, g = self._args
                return deform_conv2d(x, offset, self.weight, self.bias, s, p, d, dg, g, mask)

        return _DeformConv2D(*args, **kwargs)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale, pixel_offset=False, rois_num=None, name=None):
    rois = np.asarray(ensure_tensor(fpn_rois)._value)
    offset = 1 if pixel_offset else 0
    ws = rois[:, 2] - rois[:, 0] + offset
    hs = rois[:, 3] - rois[:, 1] + offset
    scale = np.sqrt(ws * hs)
    levels = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    levels = np.clip(levels, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for lvl in range(min_level, max_level + 1):
        sel = np.where(levels == lvl)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.concatenate(idxs) if idxs else np.zeros(0, np.int64)
    restore = np.argsort(order)
    return outs, [Tensor(jnp.asarray(np.asarray([len(i)], np.int32))) for i in idxs], Tensor(jnp.asarray(restore.astype(np.int32)))


def generate_proposals(*args, **kwargs):
    raise NotImplementedError("generate_proposals: planned (RPN-specific; layer on nms/box_coder)")


class RoIAlign:
    def __new__(cls, output_size, spatial_scale=1.0):
        from paddle_tpu.nn import Layer

        class _RoIAlign(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_align(x, boxes, boxes_num, output_size, spatial_scale)

        return _RoIAlign()


class RoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from paddle_tpu.nn import Layer

        class _RoIPool(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_pool(x, boxes, boxes_num, output_size, spatial_scale)

        return _RoIPool()


PSRoIPool = RoIPool


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode a YOLO detection head to boxes+scores (reference
    paddle.vision.ops.yolo_box, phi yolo_box kernel) — pure tensor math, so
    it is jit-traceable on TPU (the PP-YOLO family's decode stage).

    x: [N, C, H, W] with C = len(anchors)/2 * (5 + class_num);
    img_size: [N, 2] (h, w).  Returns (boxes [N, M, 4] xyxy, scores
    [N, M, class_num]) with below-threshold rows zeroed (static shape — the
    reference zeroes them too; NMS prunes downstream).
    """
    x = ensure_tensor(x)
    img_size = ensure_tensor(img_size)
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = an.shape[0]

    def _decode(xv, imgs):
        n, c, h, w = xv.shape
        if iou_aware:
            # reference channel layout: the na IoU channels come FIRST, then
            # the na*(5+class_num) box channels (yolo_box kernel)
            iou_p = jax.nn.sigmoid(xv[:, :na].reshape(n, na, h, w))
            xv = xv[:, na:]
        xv = xv.reshape(n, na, 5 + class_num, h, w)
        tx, ty, tw, th, obj = xv[:, :, 0], xv[:, :, 1], xv[:, :, 2], xv[:, :, 3], xv[:, :, 4]
        cls = xv[:, :, 5:]
        gx = jax.lax.broadcasted_iota(jnp.float32, (n, na, h, w), 3)
        gy = jax.lax.broadcasted_iota(jnp.float32, (n, na, h, w), 2)
        bx = (jax.nn.sigmoid(tx) * scale_x_y - 0.5 * (scale_x_y - 1.0) + gx) / w
        by = (jax.nn.sigmoid(ty) * scale_x_y - 0.5 * (scale_x_y - 1.0) + gy) / h
        aw = an[:, 0].reshape(1, na, 1, 1)
        ah = an[:, 1].reshape(1, na, 1, 1)
        input_w = w * downsample_ratio
        input_h = h * downsample_ratio
        bw = jnp.exp(tw) * aw / input_w
        bh = jnp.exp(th) * ah / input_h
        conf = jax.nn.sigmoid(obj)
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * iou_p ** iou_aware_factor
        probs = jax.nn.sigmoid(cls) * conf[:, :, None]
        imgs_f = imgs.astype(jnp.float32)
        im_h = imgs_f[:, 0].reshape(n, 1, 1, 1)
        im_w = imgs_f[:, 1].reshape(n, 1, 1, 1)
        x0 = (bx - bw / 2) * im_w
        y0 = (by - bh / 2) * im_h
        x1 = (bx + bw / 2) * im_w
        y1 = (by + bh / 2) * im_h
        if clip_bbox:
            x0 = jnp.clip(x0, 0, im_w - 1)
            y0 = jnp.clip(y0, 0, im_h - 1)
            x1 = jnp.clip(x1, 0, im_w - 1)
            y1 = jnp.clip(y1, 0, im_h - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(n, -1, 4)
        keep = (conf > conf_thresh).reshape(n, -1, 1)
        boxes = jnp.where(keep, boxes, 0.0)
        scores = jnp.where(keep, probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num), 0.0)
        return boxes, scores

    return apply("yolo_box", _decode, x, img_size)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0], variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False, steps=[0.0, 0.0], offset=0.5, min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) box generation (reference:
    python/paddle/vision/ops.py prior_box,
    paddle/phi/kernels/impl/prior_box_kernel_impl.h).  Pure host/np-style
    jnp math over the static feature-map grid."""
    input, image = ensure_tensor(input), ensure_tensor(image)
    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    import numpy as np

    # per-anchor (w, h) set is cell-independent: build it once, broadcast
    # against the center grid (the reference kernel's loop order, vectorized)
    whs = []
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                big = (ms * float(max_sizes[k])) ** 0.5
                whs.append((big, big))
            whs.extend((ms * ar**0.5, ms / ar**0.5) for ar in ars if abs(ar - 1.0) >= 1e-6)
        else:
            whs.extend((ms * ar**0.5, ms / ar**0.5) for ar in ars)
            if max_sizes:
                big = (ms * float(max_sizes[k])) ** 0.5
                whs.append((big, big))
    wh = np.asarray(whs, np.float32)  # [A, 2]
    cx = (np.arange(W, dtype=np.float32) + offset) * step_w
    cy = (np.arange(H, dtype=np.float32) + offset) * step_h
    b = np.empty((H, W, len(whs), 4), np.float32)
    b[..., 0] = cx[None, :, None]
    b[..., 1] = cy[:, None, None]
    b[..., 2] = wh[None, None, :, 0]
    b[..., 3] = wh[None, None, :, 1]
    out = np.empty_like(b)
    out[..., 0] = (b[..., 0] - b[..., 2] / 2) / img_w
    out[..., 1] = (b[..., 1] - b[..., 3] / 2) / img_h
    out[..., 2] = (b[..., 0] + b[..., 2] / 2) / img_w
    out[..., 3] = (b[..., 1] + b[..., 3] / 2) / img_h
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0, background_label=0, normalized=True, return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference: python/paddle/vision/ops.py matrix_nms,
    SOLOv2 paper): soft decay of scores by pairwise IoU — O(k^2) matrix math,
    no sequential suppression loop, which is exactly the TPU-friendly NMS."""
    import numpy as np

    bboxes, scores = ensure_tensor(bboxes), ensure_tensor(scores)
    bv = np.asarray(bboxes._value)  # [N, M, 4]
    sv = np.asarray(scores._value)  # [N, C, M]
    N, C, M = sv.shape
    all_out, all_idx, rois_num = [], [], []
    for n in range(N):
        dets, idxs = [], []
        for c in range(C):
            if c == background_label:
                continue
            s = sv[n, c]
            keep = np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][: int(nms_top_k) if nms_top_k > 0 else None]
            b = bv[n, order]
            sc = s[order]
            # pairwise IoU (upper triangle)
            x1 = np.maximum(b[:, None, 0], b[None, :, 0])
            y1 = np.maximum(b[:, None, 1], b[None, :, 1])
            x2 = np.minimum(b[:, None, 2], b[None, :, 2])
            y2 = np.minimum(b[:, None, 3], b[None, :, 3])
            ext = 0.0 if normalized else 1.0
            inter = np.clip(x2 - x1 + ext, 0, None) * np.clip(y2 - y1 + ext, 0, None)
            area = np.clip(b[:, 2] - b[:, 0] + ext, 0, None) * np.clip(b[:, 3] - b[:, 1] + ext, 0, None)
            union = area[:, None] + area[None, :] - inter
            iou = np.where(union > 0, inter / union, 0.0)
            iou = np.triu(iou, k=1)  # iou[i, j]: i higher-scored than j
            # SOLOv2 matrix NMS: decay_j = min_i f(iou_ij) / f(compensate_i),
            # compensate_i = that suppressor's own max IoU with anything above it
            comp = iou.max(axis=0)  # compensate per box (as a suppressor)
            if use_gaussian:
                dm = np.exp(-(iou**2 - comp[:, None] ** 2) / gaussian_sigma)
            else:
                dm = (1.0 - iou) / np.clip(1.0 - comp[:, None], 1e-10, None)
            dm = np.where(np.triu(np.ones_like(iou), k=1) > 0, dm, np.inf)
            decay = np.minimum(dm.min(axis=0), 1.0)
            dec_s = sc * decay
            sel = dec_s >= post_threshold
            for i in np.where(sel)[0]:
                dets.append([c, dec_s[i], *b[i]])
                idxs.append(n * M + order[i])
        if dets:
            dets = np.asarray(dets, np.float32)
            o = np.argsort(-dets[:, 1])
            if keep_top_k > 0:
                o = o[: int(keep_top_k)]
            dets = dets[o]
            idxs = np.asarray(idxs, np.int64)[o]
        else:
            dets = np.zeros((0, 6), np.float32)
            idxs = np.zeros((0,), np.int64)
        all_out.append(dets)
        all_idx.append(idxs)
        rois_num.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(all_out, axis=0)))
    ret = [out]
    if return_index:
        ret.append(Tensor(jnp.asarray(np.concatenate(all_idx))))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(ret) if len(ret) > 1 else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference: python/paddle/vision/ops.py
    psroi_pool, R-FCN): channel k of output bin (i, j) averages input channel
    (k*P*P + i*P + j) over that bin's region."""
    import numpy as np

    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    P = int(output_size) if not isinstance(output_size, (tuple, list)) else int(output_size[0])
    xv = np.asarray(x._value)
    bv = np.asarray(boxes._value)
    nv = np.asarray(ensure_tensor(boxes_num)._value)
    N, C, H, W = xv.shape
    out_c = C // (P * P)
    outs = []
    bi = 0
    for n in range(N):
        for _ in range(int(nv[n])):
            x1, y1, x2, y2 = bv[bi] * spatial_scale
            bi += 1
            rw = max((x2 - x1), 0.1) / P
            rh = max((y2 - y1), 0.1) / P
            o = np.zeros((out_c, P, P), np.float32)
            for i in range(P):
                for j in range(P):
                    hs, he = int(np.floor(y1 + i * rh)), int(np.ceil(y1 + (i + 1) * rh))
                    ws, we = int(np.floor(x1 + j * rw)), int(np.ceil(x1 + (j + 1) * rw))
                    hs, he = np.clip([hs, he], 0, H)
                    ws, we = np.clip([ws, we], 0, W)
                    if he > hs and we > ws:
                        for k in range(out_c):
                            ch = k * P * P + i * P + j
                            o[k, i, j] = xv[n, ch, hs:he, ws:we].mean()
            outs.append(o)
    return Tensor(jnp.asarray(np.stack(outs) if outs else np.zeros((0, out_c, P, P), np.float32)))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num, ignore_thresh, downsample_ratio, gt_score=None, use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (reference: python/paddle/vision/ops.py yolo_loss,
    paddle/phi/kernels/cpu/yolo_loss_kernel.cc): objectness + box + class
    terms against assigned anchors, jnp throughout (autodiffable)."""
    x, gt_box, gt_label = ensure_tensor(x), ensure_tensor(gt_box), ensure_tensor(gt_label)
    extras = [ensure_tensor(gt_score)] if gt_score is not None else []
    an = [float(a) for a in anchors]
    mask = [int(m) for m in anchor_mask]
    S = len(mask)
    C = int(class_num)

    def _fn(xv, gb, gl, *gs):
        N, _, H, W = xv.shape
        xv = xv.reshape(N, S, 5 + C, H, W).astype(jnp.float32)
        px, py = jax.nn.sigmoid(xv[:, :, 0]), jax.nn.sigmoid(xv[:, :, 1])
        pw, ph = xv[:, :, 2], xv[:, :, 3]
        pobj = xv[:, :, 4]
        pcls = xv[:, :, 5:]
        # grid-relative predicted boxes (normalized)
        gx = (jnp.arange(W, dtype=jnp.float32)[None, None, None, :] + px) / W
        gy = (jnp.arange(H, dtype=jnp.float32)[None, None, :, None] + py) / H
        in_w, in_h = W * downsample_ratio, H * downsample_ratio
        aw = jnp.asarray([an[2 * m] for m in mask], jnp.float32)[None, :, None, None]
        ah = jnp.asarray([an[2 * m + 1] for m in mask], jnp.float32)[None, :, None, None]
        gw = jnp.exp(pw) * aw / in_w
        gh = jnp.exp(ph) * ah / in_h
        # IoU of every predicted box with every gt box -> ignore mask
        B = gb.shape[1]
        pb = jnp.stack([gx, gy, gw, gh], axis=-1).reshape(N, -1, 4)  # [N, S*H*W, 4]
        def iou(a, b):
            ax1, ay1 = a[..., 0] - a[..., 2] / 2, a[..., 1] - a[..., 3] / 2
            ax2, ay2 = a[..., 0] + a[..., 2] / 2, a[..., 1] + a[..., 3] / 2
            bx1, by1 = b[..., 0] - b[..., 2] / 2, b[..., 1] - b[..., 3] / 2
            bx2, by2 = b[..., 0] + b[..., 2] / 2, b[..., 1] + b[..., 3] / 2
            ix = jnp.clip(jnp.minimum(ax2[:, :, None], bx2[:, None, :]) - jnp.maximum(ax1[:, :, None], bx1[:, None, :]), 0)
            iy = jnp.clip(jnp.minimum(ay2[:, :, None], by2[:, None, :]) - jnp.maximum(ay1[:, :, None], by1[:, None, :]), 0)
            inter = ix * iy
            ua = (ax2 - ax1) * (ay2 - ay1)
            ub = (bx2 - bx1) * (by2 - by1)
            return inter / jnp.clip(ua[:, :, None] + ub[:, None, :] - inter, 1e-10)
        ious = iou(pb, gb.astype(jnp.float32))  # [N, SHW, B]
        best_iou = jnp.max(ious, axis=-1).reshape(N, S, H, W)
        ignore = best_iou > ignore_thresh
        # gt assignment: each gt lands in cell (floor(gx*W), floor(gy*H)) with
        # responsible anchor = best-IoU anchor in this mask group (by shape)
        gtx, gty, gtw, gth = gb[..., 0], gb[..., 1], gb[..., 2], gb[..., 3]
        valid = gtw > 1e-8  # [N, B]
        ci = jnp.clip((gtx * W).astype(jnp.int32), 0, W - 1)
        ri = jnp.clip((gty * H).astype(jnp.int32), 0, H - 1)
        # shape-IoU with each anchor of this group
        wa = gtw[..., None] * in_w
        ha = gth[..., None] * in_h
        inter = jnp.minimum(wa, aw.reshape(1, 1, S)) * jnp.minimum(ha, ah.reshape(1, 1, S))
        s_iou = inter / jnp.clip(wa * ha + aw.reshape(1, 1, S) * ah.reshape(1, 1, S) - inter, 1e-10)
        best_a = jnp.argmax(s_iou, axis=-1)  # [N, B]
        # scatter targets
        tobj = jnp.zeros((N, S, H, W))
        bidx = jnp.arange(N)[:, None].repeat(gb.shape[1], 1)
        w_obj = gs[0].astype(jnp.float32) if gs else jnp.ones_like(gtx)
        w_obj = jnp.where(valid, w_obj, 0.0)
        tobj = tobj.at[bidx, best_a, ri, ci].max(w_obj)
        tx = gtx * W - ci
        ty = gty * H - ri
        tw = jnp.log(jnp.clip(gtw * in_w / jnp.take(aw.reshape(-1), best_a), 1e-9))
        th = jnp.log(jnp.clip(gth * in_h / jnp.take(ah.reshape(-1), best_a), 1e-9))
        box_scale = 2.0 - gtw * gth
        def at_cells(pred):
            return pred[bidx, best_a, ri, ci]
        bce = lambda lo, t: jnp.maximum(lo, 0) - lo * t + jnp.log1p(jnp.exp(-jnp.abs(lo)))
        vm = w_obj
        loss_xy = jnp.sum((bce(at_cells(xv[:, :, 0]), tx) + bce(at_cells(xv[:, :, 1]), ty)) * box_scale * vm, axis=1)
        loss_wh = jnp.sum((jnp.abs(at_cells(pw) - tw) + jnp.abs(at_cells(ph) - th)) * box_scale * vm, axis=1)
        obj_mask = tobj > 0
        loss_obj = jnp.sum(bce(pobj, tobj) * jnp.where(~obj_mask & ignore, 0.0, 1.0), axis=(1, 2, 3))
        smooth = 1.0 / C if use_label_smooth else 0.0
        tcls = jax.nn.one_hot(gl.astype(jnp.int32), C) * (1.0 - smooth) + smooth / 2.0
        pcls_cells = jnp.transpose(pcls, (0, 1, 3, 4, 2))[bidx, best_a, ri, ci]
        loss_cls = jnp.sum(jnp.sum(bce(pcls_cells, tcls), axis=-1) * vm, axis=1)
        return (loss_xy + loss_wh + loss_obj + loss_cls).astype(jnp.float32)

    return apply("yolo_loss", _fn, x, gt_box, gt_label, *extras)


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (reference: paddle.vision.ops.read_file)."""
    import numpy as np

    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference:
    paddle.vision.ops.decode_jpeg over nvjpeg).  Host-side decode via PIL —
    image IO is a host job on TPU; the device path starts at the batch."""
    import io

    import numpy as np

    x = ensure_tensor(x)
    data = bytes(np.asarray(x._value).astype(np.uint8))
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg needs Pillow on the host") from e
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class PSRoIPool:
    """Layer wrapper over psroi_pool (reference: paddle.vision.ops.PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)
